"""End-to-end observability plane: distributed tracing (ids, sampling,
wire propagation, cross-process graft), per-span cost attribution from
QueryScope charges, the slow-query log's typed reasons, the self-scrape
pipeline (instrument snapshot -> own ingest -> PromQL), JAX runtime
telemetry, and the /debug surface satellites (snapshot-outside-lock,
capped background profiler)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from m3_tpu.utils import tracing
from m3_tpu.utils.tracing import (NOOP_SPAN, PROFILER, SLOW_QUERIES,
                                  ProfileRunner, SlowQueryLog, SpanContext,
                                  Tracer)

T0 = 1_700_000_000 * 1_000_000_000
S = 1_000_000_000


# ---------------------------------------------------------------- tracer core


class TestSpanIdentity:
    def test_root_gets_trace_and_span_ids(self):
        tr = Tracer(sample_rate=1.0)
        with tr.span("root") as root:
            assert root.trace_id > 0 and root.span_id > 0
            with tr.span("child") as c:
                assert c.trace_id == root.trace_id
                assert c.span_id != root.span_id
        d = tr.recent_traces()[-1]
        assert d["trace_id"] == root.trace_id
        assert d["children"][0]["trace_id"] == root.trace_id

    def test_context_wire_roundtrip_and_malformed(self):
        ctx = SpanContext(123, 456)
        assert SpanContext.from_wire(ctx.to_wire()) == ctx
        for bad in (None, 7, {"t": "x", "s": 1}, {"t": 1}, {"s": 2},
                    {"t": True, "s": 1}, []):
            assert SpanContext.from_wire(bad) is None

    def test_sampling_zero_yields_noop(self):
        tr = Tracer(sample_rate=0.0)
        sp = tr.span("never")
        assert sp is NOOP_SPAN
        with sp:
            assert tr.current() is None
        assert tr.recent_traces() == []

    def test_child_span_without_parent_is_noop(self):
        tr = Tracer(sample_rate=1.0)
        assert tr.child_span("bare") is NOOP_SPAN
        with tr.span("root"):
            real = tr.child_span("inner")
            assert real is not NOOP_SPAN
            with real:
                pass

    def test_span_from_remote_context(self):
        tr = Tracer(sample_rate=1.0)
        ctx = SpanContext(99, 11)
        with tr.span_from(ctx, "rpc.x") as sp:
            assert sp.trace_id == 99
            assert sp.remote_parent == 11
        d = tr.recent_traces(trace_id=99)
        assert d and d[-1]["remote_parent"] == 11
        assert tr.span_from(None, "rpc.x") is NOOP_SPAN

    def test_activate_propagates_across_threads(self):
        tr = Tracer(sample_rate=1.0)
        seen = {}

        with tr.span("root") as root:
            def worker():
                with tr.activate(root):
                    seen["cur"] = tr.current()
                    with tr.span("in-pool"):
                        pass
                seen["after"] = tr.current()

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["cur"] is root
        assert seen["after"] is None
        d = tr.recent_traces()[-1]
        assert [c["name"] for c in d["children"]] == ["in-pool"]

    def test_attach_grafts_remote_dict(self):
        tr = Tracer(sample_rate=1.0)
        with tr.span("root") as root:
            root.attach({"name": "rpc.fetch", "trace_id": root.trace_id,
                         "tags": {"endpoint": "h:1"}})
        d = tr.recent_traces()[-1]
        assert d["children"][0]["name"] == "rpc.fetch"

    def test_collect_costs_rolls_up_subtree_and_grafts(self):
        """Review fix: cache events accrue on the INNERMOST span (a
        storage child, or a grafted remote dict) — the slow-query log's
        cold-cache classification reads the subtree rollup."""
        tr = Tracer(sample_rate=1.0)
        with tr.span("root") as root:
            root.add_cost("docs_matched", 5)
            with tr.span("child") as c:
                c.add_cost("block_cache_miss", 2)
            root.attach({"name": "rpc", "costs": {"bytes_read": 7},
                         "children": [{"name": "x",
                                       "costs": {"block_cache_miss": 1}}]})
        assert tracing.collect_costs(root) == {
            "docs_matched": 5, "block_cache_miss": 3, "bytes_read": 7}

    def test_slow_log_lazy_costs_only_evaluated_on_record(self):
        log = SlowQueryLog(threshold_ms=1.0)
        calls = []

        def expensive():
            calls.append(1)
            return {"block_cache_miss": 1}

        log.maybe("query", "fast", duration_ns=100, costs=expensive)
        assert calls == []  # under threshold: rollup never ran
        log.maybe("query", "slow", duration_ns=5_000_000, costs=expensive)
        assert calls == [1]
        assert log.entries()[-1]["reason"] == "cold-cache"

    def test_costs_accumulate(self):
        tr = Tracer(sample_rate=1.0)
        with tr.span("root") as root:
            root.add_cost("bytes_read", 10)
            root.add_cost("bytes_read", 5)
        assert tr.recent_traces()[-1]["costs"] == {"bytes_read": 15}


# ------------------------------------------------------------ slow-query log


class TestSlowQueryLog:
    def test_threshold_and_typed_reasons(self):
        log = SlowQueryLog(threshold_ms=1.0, maxlen=8)
        log.maybe("query", "fast", duration_ns=10_000)  # under threshold
        log.maybe("query", "slow_one", duration_ns=5_000_000)
        log.maybe("query", "shed", duration_ns=10, reason="limit-shed")
        log.maybe("query", "dead", duration_ns=10, reason="deadline")
        entries = log.entries()
        assert [e["name"] for e in entries] == ["slow_one", "shed", "dead"]
        assert [e["reason"] for e in entries] == ["slow", "limit-shed",
                                                 "deadline"]

    def test_cold_cache_reason_from_costs(self):
        log = SlowQueryLog(threshold_ms=0.0)
        log.maybe("query", "q", duration_ns=1,
                  costs={"block_cache_miss": 2, "bytes_read": 5})
        log.maybe("query", "warm", duration_ns=1, costs={"bytes_read": 5})
        assert log.entries()[0]["reason"] == "cold-cache"
        assert log.entries()[1]["reason"] == "slow"

    def test_ring_bounded(self):
        log = SlowQueryLog(threshold_ms=0.0, maxlen=4)
        for i in range(10):
            log.maybe("rpc", f"m{i}", duration_ns=1)
        assert len(log.entries()) == 4
        assert log.entries()[-1]["name"] == "m9"


# --------------------------------------------------- cross-process span trees


def _node_with_data():
    from m3_tpu.parallel.sharding import ShardSet
    from m3_tpu.rpc import NodeServer, NodeService
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.namespace import NamespaceOptions

    db = Database(ShardSet(2), clock=lambda: T0)
    db.mark_bootstrapped()
    db.ensure_namespace(b"obs", NamespaceOptions(index_enabled=True,
                                                 writes_to_commitlog=False))
    for i in range(6):
        db.write(b"obs", b"s-%02d" % i, T0 - (6 - i) * S, float(i),
                 tags={b"__name__": b"m", b"host": b"h%02d" % i})
    return NodeServer(NodeService(db), port=0).start()


class TestCrossProcessTrace:
    def test_rpc_span_grafted_with_costs_and_storage_children(self):
        from m3_tpu.client.session import HostClient
        from m3_tpu.index import query as iq
        from m3_tpu.rpc import wire

        srv = _node_with_data()
        hc = HostClient(srv.endpoint, timeout=5)
        try:
            with tracing.TRACER.span("test.root") as root:
                r = hc.call("fetch_tagged", ns=b"obs",
                            query=wire.query_to_wire(iq.AllQuery()),
                            start_ns=0, end_ns=2**62)
                assert len(r["series"]) == 6
                grafted = [c for c in root.children if isinstance(c, dict)]
            assert grafted, "no server span grafted"
            sp = grafted[0]
            assert sp["name"] == "rpc.fetch_tagged"
            assert sp["trace_id"] == root.trace_id
            assert sp["remote_parent"] == root.span_id
            assert sp["tags"]["endpoint"] == srv.endpoint
            # per-span QueryScope cost attribution rode the graft
            assert sp["costs"]["series_fetched"] == 6
            assert sp["costs"]["docs_matched"] >= 6
            assert sp["costs"]["bytes_read"] > 0
            # dbnode-side storage child (index query) inside the rpc span
            names = [c["name"] for c in sp.get("children", [])]
            assert "index.query" in names
        finally:
            hc.close()
            srv.close()

    def test_unsampled_request_attaches_no_context(self):
        from m3_tpu.client.session import HostClient

        srv = _node_with_data()
        hc = HostClient(srv.endpoint, timeout=5)
        try:
            before = len(tracing.TRACER.recent_traces())
            assert hc.call("health")["ok"]  # no active span -> no "tr"
            after = [d for d in tracing.TRACER.recent_traces()[before:]
                     if d["name"].startswith("rpc.")]
            assert after == []
        finally:
            hc.close()
            srv.close()

    def test_session_fetch_tagged_one_tree_three_hops(self):
        from m3_tpu.client.session import Session, SessionOptions
        from m3_tpu.index import query as iq
        from m3_tpu.testing.cluster import ClusterHarness

        harness = ClusterHarness(n_nodes=2, replica_factor=2, num_shards=4)
        session = Session(harness.topology, SessionOptions(timeout_s=10))
        try:
            t0 = harness.clock.now_ns
            session.write_batch(
                b"default", [b"a", b"b"], np.array([t0 - S] * 2, np.int64),
                np.array([1.0, 2.0]),
                tags=[{b"__name__": b"mm"}, {b"__name__": b"mm"}])
            with tracing.TRACER.span("test.query") as root:
                out = session.fetch_tagged(b"default", iq.AllQuery(),
                                           0, 2**62)
                assert len(out) == 2
            d = root.to_dict()
            client = d["children"][0]
            assert client["name"] == "client.fetch_tagged"
            grafts = [c for c in client.get("children", [])
                      if c.get("name") == "rpc.fetch_tagged"]
            assert grafts, "no dbnode spans under the client fanout span"
            # one trace id across client + every grafted dbnode span
            assert {g["trace_id"] for g in grafts} == {root.trace_id}
            endpoints = {g["tags"]["endpoint"] for g in grafts}
            assert len(endpoints) == 2  # both replicas traced
        finally:
            session.close()
            harness.close()

    def test_gate_shed_logs_empty_costs_not_previous_requests(self):
        """Review fix: a request shed by the admission gate BEFORE its
        QueryScope runs must log empty costs — not the previous
        request's totals left on this reused serving thread."""
        from m3_tpu.index import query as iq
        from m3_tpu.rpc import wire
        from m3_tpu.rpc.node_server import NodeService
        from m3_tpu.parallel.sharding import ShardSet
        from m3_tpu.storage.database import Database
        from m3_tpu.storage.namespace import NamespaceOptions
        from m3_tpu.utils.health import AdmissionGate, HealthTracker
        from m3_tpu.utils.limits import ResourceExhausted

        db = Database(ShardSet(2), clock=lambda: T0)
        db.mark_bootstrapped()
        db.ensure_namespace(b"obs", NamespaceOptions(index_enabled=True))
        db.write(b"obs", b"g-0", T0, 1.0, tags={b"__name__": b"m"})
        gate = AdmissionGate(capacity=2, name="",
                             tracker=HealthTracker())
        svc = NodeService(db, gate=gate)
        q = wire.query_to_wire(iq.AllQuery())
        # Request A charges real costs on this thread.
        svc.dispatch("fetch_tagged",
                     {"ns": b"obs", "query": q, "start_ns": 0,
                      "end_ns": 2**62})
        # Fill the gate so request B sheds BEFORE its scope runs.
        gate.admit(2)
        SLOW_QUERIES.clear()
        try:
            with pytest.raises(ResourceExhausted):
                svc.dispatch("fetch_tagged",
                             {"ns": b"obs", "query": q, "start_ns": 0,
                              "end_ns": 2**62})
        finally:
            gate.release(2)
        sheds = [e for e in SLOW_QUERIES.entries()
                 if e["reason"] == "limit-shed"]
        assert sheds and sheds[-1]["costs"] == {}

    def test_slow_log_limit_shed_reason_from_rpc(self):
        from m3_tpu.client.session import HostClient
        from m3_tpu.index import query as iq
        from m3_tpu.rpc import NodeServer, NodeService, wire
        from m3_tpu.parallel.sharding import ShardSet
        from m3_tpu.storage.database import Database
        from m3_tpu.storage.namespace import NamespaceOptions
        from m3_tpu.utils.limits import (LimitOptions, QueryLimits,
                                         ResourceExhausted)

        db = Database(ShardSet(2), clock=lambda: T0)
        db.mark_bootstrapped()
        db.ensure_namespace(b"obs", NamespaceOptions(index_enabled=True))
        for i in range(20):
            db.write(b"obs", b"x-%02d" % i, T0, 1.0,
                     tags={b"__name__": b"m"})
        limits = QueryLimits(docs_matched=LimitOptions(per_second=5))
        srv = NodeServer(NodeService(db, limits=limits), port=0).start()
        hc = HostClient(srv.endpoint, timeout=5)
        SLOW_QUERIES.clear()
        try:
            with pytest.raises(ResourceExhausted):
                hc.call("fetch_tagged", ns=b"obs",
                        query=wire.query_to_wire(iq.AllQuery()),
                        start_ns=0, end_ns=2**62)
            sheds = [e for e in SLOW_QUERIES.entries()
                     if e["reason"] == "limit-shed"]
            assert sheds and sheds[-1]["kind"] == "rpc"
        finally:
            hc.close()
            srv.close()


# ------------------------------------------------------- scope cost tagging


class TestScopeCostTagging:
    def test_scope_exit_annotates_active_span(self):
        from m3_tpu.utils import limits as xlimits

        ql = xlimits.QueryLimits()
        with tracing.TRACER.span("q") as sp:
            with ql.scope("test"):
                xlimits.charge("docs_matched", 7)
                xlimits.charge("bytes_read", 100)
                xlimits.charge("docs_matched", 3)
        assert sp.costs["docs_matched"] == 10
        assert sp.costs["bytes_read"] == 100
        # thread-local totals readable after exit (slow-log source)
        assert xlimits.last_scope_totals()["docs_matched"] == 10


# ----------------------------------------------------------- self-scrape


def _embedded():
    from m3_tpu.cluster import kv as cluster_kv
    from m3_tpu.coordinator import run_embedded
    from m3_tpu.index.namespace_index import NamespaceIndex
    from m3_tpu.parallel.sharding import ShardSet
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.namespace import NamespaceOptions

    now = {"t": T0}
    db = Database(ShardSet(4), clock=lambda: now["t"])
    db.create_namespace(b"default", NamespaceOptions(),
                        index=NamespaceIndex(clock=lambda: now["t"]))
    coord = run_embedded(db, kv_store=cluster_kv.MemStore(),
                         clock=lambda: now["t"])
    return coord, now


class TestSelfScrape:
    def test_traffic_counter_round_trip_via_promql(self):
        """THE acceptance criterion: an instrument counter incremented
        by real traffic is readable back through the PromQL query path
        against the platform's own storage."""
        from m3_tpu.coordinator.selfscrape import SelfScraper
        from m3_tpu.utils.instrument import ROOT

        coord, now = _embedded()
        try:
            coord.writer.write({b"__name__": b"real"}, T0 - 30 * S, 1.0)
            coord.engine.execute_range("real", T0 - 60 * S, T0, 10 * S)
            executed = ROOT.snapshot()["query.executed"]
            scraper = SelfScraper(coord.writer, clock=lambda: now["t"])
            assert scraper.scrape_once() > 0
            blk = coord.engine.execute_instant("query_executed", T0 + 1)
            assert blk.n_series == 1
            assert blk.values[0][-1] >= executed
            # constant labels identify the scraped process
            assert blk.series_tags[0].get(b"role") == b"coordinator"
        finally:
            coord.close()

    def test_snapshot_delta_skips_unchanged(self):
        from m3_tpu.coordinator.selfscrape import SelfScraper

        coord, now = _embedded()
        try:
            coord.writer.write({b"__name__": b"real"}, T0 - 30 * S, 1.0)
            scraper = SelfScraper(coord.writer, clock=lambda: now["t"])
            first = scraper.scrape_once()
            assert first > 0
            # a second immediate scrape only re-emits what the FIRST
            # scrape itself moved (its own ingest counters), a strict
            # subset of the full registry
            second = scraper.scrape_once()
            assert second < first
        finally:
            coord.close()

    def test_histogram_emits_le_buckets(self):
        from m3_tpu.coordinator.selfscrape import SelfScraper

        coord, now = _embedded()
        try:
            coord.writer.write({b"__name__": b"real"}, T0 - 30 * S, 1.0)
            coord.engine.execute_range("real", T0 - 60 * S, T0, 10 * S)
            SelfScraper(coord.writer,
                        clock=lambda: now["t"]).scrape_once()
            blk = coord.engine.execute_instant(
                'query_latency_s_bucket{le="+Inf"}', T0 + 1)
            assert blk.n_series >= 1
            cnt = coord.engine.execute_instant("query_latency_s_count",
                                               T0 + 1)
            assert cnt.n_series == 1 and cnt.values[0][-1] >= 1
        finally:
            coord.close()

    def test_shed_value_reemits_next_pass(self):
        """Review fix: a value whose write was shed must NOT be marked
        done — if it then stays flat, the next pass re-emits it (the
        'levels, nothing is lost' contract)."""
        from m3_tpu.coordinator.selfscrape import SelfScraper
        from m3_tpu.utils.instrument import Scope

        root = Scope()
        root.counter("stuck").inc(5)

        class FlakyWriter:
            def __init__(self):
                self.fail_first = True
                self.names = []

            def write(self, tags, t_ns, value):
                if self.fail_first:
                    self.fail_first = False
                    raise ConnectionError("down")
                self.names.append(tags[b"__name__"])

        w = FlakyWriter()
        scraper = SelfScraper(w, clock=lambda: T0, scope=root)
        scraper.scrape_once()
        assert b"stuck" not in w.names  # first emit was shed
        scraper.scrape_once()           # value unchanged — must re-emit
        assert b"stuck" in w.names

    def test_shed_scrape_survives(self):
        """A writer that sheds (Backpressure) must not kill the scrape:
        errors count, the pass completes, levels re-emit next pass."""
        from m3_tpu.coordinator.selfscrape import SelfScraper
        from m3_tpu.utils.limits import Backpressure

        class SheddingWriter:
            def __init__(self):
                self.n = 0

            def write(self, tags, t_ns, value):
                self.n += 1
                if self.n % 2:
                    raise Backpressure("shed")

        w = SheddingWriter()
        scraper = SelfScraper(w, clock=lambda: T0)
        scraper.scrape_once()
        assert scraper.errors > 0
        assert w.n > 0


# ------------------------------------------------------------- telemetry


class TestTelemetry:
    def test_jit_builder_counts_and_times_compiles(self):
        import functools

        from m3_tpu.parallel import telemetry
        from m3_tpu.utils.instrument import ROOT

        calls = []

        @telemetry.jit_builder("obs_test")
        @functools.lru_cache(maxsize=8)
        def build(w: int):
            calls.append(w)
            return lambda x: x * w

        before = ROOT.snapshot()
        f = build(3)
        assert f(2) == 6  # first call -> compile timed
        assert f(2) == 6
        g = build(3)      # hit: raw fn, same result
        assert g(2) == 6
        build(4)
        snap = ROOT.snapshot()
        key_m = "telemetry.jit.misses{builder=obs_test}"
        key_h = "telemetry.jit.hits{builder=obs_test}"
        assert snap[key_m] - before.get(key_m, 0) == 2
        assert snap[key_h] - before.get(key_h, 0) == 1
        assert calls == [3, 4]
        assert snap["telemetry.jit.compile_s"]["count"] >= 1

    def test_jit_builder_rejects_unwrapped(self):
        from m3_tpu.parallel import telemetry

        with pytest.raises(TypeError):
            telemetry.jit_builder("bad")(lambda: None)

    def test_shape_bucket_hit_miss(self):
        from m3_tpu.parallel import telemetry
        from m3_tpu.utils.instrument import ROOT

        key = ("test-path", (64, 32, int(time.monotonic_ns())))
        before = ROOT.snapshot().get("telemetry.shape_bucket.misses", 0)
        telemetry.record_bucket(*key)
        telemetry.record_bucket(*key)
        snap = ROOT.snapshot()
        assert snap["telemetry.shape_bucket.misses"] == before + 1
        assert snap["telemetry.shape_bucket.hits"] >= 1

    def test_transfer_counters_and_span_costs(self):
        from m3_tpu.parallel import telemetry
        from m3_tpu.utils.instrument import ROOT

        before = ROOT.snapshot().get("telemetry.transfer.h2d_bytes", 0)
        with tracing.TRACER.span("xfer") as sp:
            telemetry.count_h2d(1024)
            telemetry.count_d2h(2048)
        snap = ROOT.snapshot()
        assert snap["telemetry.transfer.h2d_bytes"] == before + 1024
        assert sp.costs == {"h2d_bytes": 1024, "d2h_bytes": 2048}

    def test_decode_records_bucket(self):
        from m3_tpu.client.decode import decode_segment_groups
        from m3_tpu.ops import tsz
        from m3_tpu.utils.instrument import ROOT

        ts = np.arange(T0, T0 + 5 * S, S, np.int64)
        vals = np.arange(5, dtype=np.float64)
        inp = tsz.prepare_encode_inputs(ts[None, :], vals[None, :],
                                        np.array([5], np.int32))
        words, nbits = tsz.encode_batch(
            inp["dt"], inp["t0"], inp["vhi"], inp["vlo"], inp["int_mode"],
            inp["k"], inp["npoints"], inp["ts_regular"], inp["delta0"],
            max_words=64)
        seg = {"bs": T0, "words": np.asarray(words[0]),
               "nbits": int(nbits[0]), "npoints": 5, "window": 8,
               "time_unit": 4}
        before = ROOT.snapshot().get("telemetry.shape_bucket.misses", 0)
        out = decode_segment_groups([seg])
        np.testing.assert_array_equal(out[0][1], vals)
        after = ROOT.snapshot()["telemetry.shape_bucket.misses"]
        assert after >= before  # first geometry may or may not be new
        snap = ROOT.snapshot()
        assert (snap.get("telemetry.shape_bucket.misses{path=client.decode}",
                         0)
                + snap.get("telemetry.shape_bucket.hits{path=client.decode}",
                           0)) >= 1


# ------------------------------------------------- /debug surface satellites


class TestInstrumentSnapshotLock:
    def test_snapshot_does_not_hold_root_lock_over_metric_snapshots(self):
        """Satellite: Scope.snapshot copies refs under the registry lock
        and snapshots outside it — a Histogram whose snapshot itself
        touches the registry (nested root-lock acquisition, guaranteed
        deadlock pre-fix on the non-reentrant Lock) must complete."""
        from m3_tpu.utils.instrument import Scope

        root = Scope()
        h = root.histogram("lat")
        h.record(0.5)
        orig = h.snapshot

        def reentrant_snapshot():
            root.counter("probe").inc()  # takes the root registry lock
            return orig()

        h.snapshot = reentrant_snapshot
        done = {}

        def run():
            done["snap"] = root.snapshot()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout=5)
        assert "snap" in done, "snapshot deadlocked on the registry lock"
        assert done["snap"]["lat"]["count"] == 1

    def test_histogram_snapshot_consistent_under_writes(self):
        from m3_tpu.utils.instrument import Histogram

        h = Histogram()
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                h.record(0.01)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            for _ in range(200):
                snap = h.snapshot()
                assert sum(snap["buckets"].values()) == snap["count"]
        finally:
            stop.set()
            t.join()


class TestProfileRunner:
    def test_hard_cap_bounds_the_request(self):
        runner = ProfileRunner(max_seconds=0.3)
        t0 = time.perf_counter()
        out = runner.run(seconds=30.0, hz=50)
        assert time.perf_counter() - t0 < 2.0
        assert isinstance(out, list)

    def test_concurrent_requests_share_one_window(self):
        runner = ProfileRunner(max_seconds=0.5)
        results = []

        def req():
            results.append(runner.run(seconds=0.4, hz=100))

        threads = [threading.Thread(target=req) for _ in range(4)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # 4 requests of 0.4s each sharing one window: far under 4x serial
        assert time.perf_counter() - t0 < 1.5
        assert runner.shared >= 1
        assert len(results) == 4

    def test_default_runner_profiles(self):
        stop = threading.Event()

        def hot_loop_for_runner():
            x = 0
            while not stop.is_set():
                x += 1

        t = threading.Thread(target=hot_loop_for_runner)
        t.start()
        try:
            out = PROFILER.run(seconds=0.3, hz=200)
        finally:
            stop.set()
            t.join()
        assert "hot_loop_for_runner" in json.dumps(out)


# ---------------------------------------------------- msg / kv propagation


class TestMsgKvPropagation:
    def test_producer_consumer_joins_trace(self):
        from m3_tpu.msg.consumer import Consumer
        from m3_tpu.msg.producer import Producer
        from m3_tpu.msg.topic import ConsumerService, ConsumptionType, Topic
        from m3_tpu.cluster.placement import Instance, initial_placement

        got = threading.Event()
        consumer = Consumer(lambda shard, val: got.set(), ack_batch=1)
        consumer.start()
        placement = initial_placement(
            [Instance(id="c0", endpoint=consumer.endpoint)], num_shards=1,
            replica_factor=1)
        topic = Topic("t", 1, [ConsumerService("svc",
                                               ConsumptionType.SHARED)])
        producer = Producer(topic, {"svc": lambda: placement})
        try:
            with tracing.TRACER.span("publish.root") as root:
                producer.publish(0, b"payload")
            assert got.wait(5.0)
            deadline = time.monotonic() + 5.0
            consumed = []
            while time.monotonic() < deadline and not consumed:
                consumed = [d for d in tracing.TRACER.recent_traces(
                    trace_id=root.trace_id) if d["name"] == "msg.consume"]
                time.sleep(0.01)
            assert consumed, "consumer span did not join the trace"
            assert consumed[-1]["remote_parent"] == root.span_id
        finally:
            producer.close()
            consumer.close()

    def test_kv_ops_graft_server_span(self):
        from m3_tpu.cluster.kv_service import KVServer, RemoteStore

        srv = KVServer().start()
        store = RemoteStore(srv.endpoint)
        try:
            with tracing.TRACER.span("kv.root") as root:
                store.set("k", b"v")
                assert store.get("k").data == b"v"
            grafted = [c for c in root.children if isinstance(c, dict)]
            names = {g["name"] for g in grafted}
            assert "kv.set" in names and "kv.get" in names
            assert all(g["trace_id"] == root.trace_id for g in grafted)
        finally:
            store.close()
            srv.close()


# -------------------------------------------------------- HTTP debug surface


class TestHTTPSurface:
    def test_coordinator_debug_traces_slow_and_trace_filter(self):
        coord, now = _embedded()
        try:
            old = SLOW_QUERIES.threshold_ns
            SLOW_QUERIES.threshold_ns = 0
            try:
                coord.writer.write({b"__name__": b"real"}, T0 - 30 * S, 1.0)
                coord.engine.execute_range("real", T0 - 60 * S, T0, 10 * S)
            finally:
                SLOW_QUERIES.threshold_ns = old
            d = json.load(urllib.request.urlopen(
                coord.endpoint + "/debug/traces"))
            assert "slow" in d
            entry = [e for e in d["slow"] if e["name"] == "real"][-1]
            assert entry["reason"] in ("slow", "cold-cache")
            assert entry["costs"].get("datapoints_decoded", 0) >= 1
            roots = [t for t in d["traces"]
                     if t["name"] == "query.execute_range"]
            tid = roots[-1]["trace_id"]
            filtered = json.load(urllib.request.urlopen(
                coord.endpoint + f"/debug/traces?trace_id={tid}"))
            assert all(t["trace_id"] == tid for t in filtered["traces"])
        finally:
            coord.close()

    def test_http_trace_header_ingress(self):
        coord, now = _embedded()
        try:
            req = urllib.request.Request(coord.endpoint + "/health")
            req.add_header("X-M3-Trace", "777:42")
            urllib.request.urlopen(req)
            spans = tracing.TRACER.recent_traces(trace_id=777)
            assert spans and spans[-1]["name"].startswith("http.GET")
            assert spans[-1]["remote_parent"] == 42
        finally:
            coord.close()

    def test_dbnode_httpjson_debug_surface(self):
        from m3_tpu.rpc.httpjson import HTTPJSONServer
        from m3_tpu.rpc.node_server import NodeService
        from m3_tpu.parallel.sharding import ShardSet
        from m3_tpu.storage.database import Database

        db = Database(ShardSet(2), clock=lambda: T0)
        db.mark_bootstrapped()
        srv = HTTPJSONServer(NodeService(db)).start()
        try:
            dvars = json.load(urllib.request.urlopen(
                srv.endpoint + "/debug/vars"))
            assert "metrics" in dvars
            traces = json.load(urllib.request.urlopen(
                srv.endpoint + "/debug/traces"))
            assert "traces" in traces and "slow" in traces
            prof = json.load(urllib.request.urlopen(
                srv.endpoint + "/debug/pprof/profile?seconds=0.1"))
            assert "profile" in prof
            # malformed params answer a typed 400, never a dropped conn
            try:
                urllib.request.urlopen(
                    srv.endpoint + "/debug/pprof/profile?seconds=abc")
                assert False, "expected HTTP 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
            stacks = urllib.request.urlopen(
                srv.endpoint + "/debug/pprof/threads").read().decode()
            assert "--- thread" in stacks
        finally:
            srv.close()
