"""Concurrency sweep over one database: write + tick + flush + cold read +
index query hammering the same namespace simultaneously for a few seconds
(the closest Python gets to running the suite under -race; reference:
src/dbnode/storage/shard_race_prop_test.go and TESTING.md's -race policy).

Invariants asserted DURING the storm (torn-read detection) and after it
(lost-point detection):
  * a read never returns a value that was not written for that series at
    that timestamp (no torn/garbage reads);
  * read timestamps are strictly increasing (no interleaving corruption);
  * after the storm, every surviving (series, ts) -> value pair is exactly
    the last value written (no lost writes), through whatever mix of warm
    buffers and flushed+evicted blocks the storm produced;
  * the reverse index serves every written series id throughout.
"""

import threading
import time

import numpy as np
import pytest

from m3_tpu.index.namespace_index import NamespaceIndex
from m3_tpu.index.query import TermQuery
from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.persist.fs import PersistManager
from m3_tpu.storage.database import Database
from m3_tpu.storage.namespace import NamespaceOptions
from m3_tpu.utils import xtime

S = 1_000_000_000
T0 = 1_700_000_000 * S
SPEEDUP = 600  # virtual seconds per wall second: windows close mid-storm


def test_concurrent_write_tick_flush_read_query(tmp_path):
    wall0 = time.time()

    def clock():
        return T0 + int((time.time() - wall0) * SPEEDUP * S)

    opts = NamespaceOptions(
        block_size_ns=10 * xtime.MINUTE,
        buffer_past_ns=5 * xtime.MINUTE,
        buffer_future_ns=5 * xtime.MINUTE,
        writes_to_commitlog=False,
    )
    db = Database(ShardSet(8), clock=clock)
    db.create_namespace(b"default", opts, index=NamespaceIndex(clock=clock))
    db.mark_bootstrapped()
    pm = PersistManager(str(tmp_path))
    from m3_tpu.storage.retriever import BlockRetriever

    db.set_retriever(BlockRetriever(pm))  # cold reads serve evicted blocks

    n_writers, series_per_writer = 3, 6
    stop = threading.Event()
    errors = []
    # expectations[sid][t] = every value written at t, in write order
    # (writers own disjoint series, so "last" is well defined per thread).
    # Mid-storm reads may see any prefix's latest; the post-storm check
    # demands exactly the final value.
    expectations = [dict() for _ in range(n_writers * series_per_writer)]
    # indexed[si] turns True only after a write for si has RETURNED, so a
    # querier that snapshots it before querying has a sound lower bound on
    # what the reverse index must contain.
    indexed = [False] * (n_writers * series_per_writer)
    all_sids = [b"sweep-%d" % i for i in range(n_writers * series_per_writer)]

    def guard(fn):
        def run():
            try:
                while not stop.is_set():
                    fn()
            except Exception as e:  # noqa: BLE001 - surface in main thread
                errors.append(e)
                stop.set()
        return run

    def writer(widx):
        seq = [0]
        mine = list(range(widx * series_per_writer,
                          (widx + 1) * series_per_writer))

        def write_once():
            for si in mine:
                # Quantize to whole virtual seconds: the codec's DoD ticks
                # are int32 per time unit, and raw-ns jitter would force
                # the NANOSECOND unit where scheduler gaps overflow it.
                t = clock() // S * S
                v = float(widx * 1_000_000 + seq[0])
                # Record BEFORE the write: a reader racing the write must
                # find the value already in the expectation map.
                expectations[si].setdefault(t, []).append(v)
                db.write(b"default", all_sids[si], t, v,
                         tags={b"__name__": b"sweep",
                               b"w": str(widx).encode()})
                indexed[si] = True
                seq[0] += 1
        return write_once

    def ticker():
        db.tick()
        time.sleep(0.01)

    def flusher():
        db.flush(pm)
        db.evict_flushed()
        time.sleep(0.05)

    def reader():
        si = np.random.randint(len(all_sids))
        t_now = clock()
        pts = db.read(b"default", all_sids[si], T0, t_now + S)
        ts, vals = pts if isinstance(pts, tuple) else (pts[0], pts[1])
        ts = np.asarray(ts)
        vals = np.asarray(vals)
        if ts.size > 1 and not (np.diff(ts) > 0).all():
            raise AssertionError(f"non-monotone read ts for {all_sids[si]}")
        exp = expectations[si]
        for t, v in zip(ts.tolist(), vals.tolist()):
            # Writer may have recorded t AFTER we read; only check points
            # the expectation map already holds. Any value ever written at
            # t is a valid racy read; anything else is torn/garbage.
            want = exp.get(t)
            if want is not None and v not in want:
                raise AssertionError(
                    f"torn read {all_sids[si]} t={t}: got {v} want {want}")

    def querier():
        flags = list(indexed)  # snapshot BEFORE the query (sound bound)
        res = db.query_ids(b"default", TermQuery(b"__name__", b"sweep"))
        got = set(res)
        # every series whose write completed before the query must serve
        for si, sid in enumerate(all_sids):
            if flags[si] and sid not in got:
                raise AssertionError(f"index lost {sid}")
        time.sleep(0.01)

    threads = [threading.Thread(target=guard(writer(w))) for w in range(n_writers)]
    threads += [threading.Thread(target=guard(fn))
                for fn in (ticker, flusher, reader, reader, querier)]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "sweep thread hung"
    if errors:
        raise errors[0]

    # Post-storm: no lost writes anywhere in the retention window, through
    # whatever warm/flushed/evicted state each block ended up in.
    t_end = clock() + S
    total_checked = 0
    for si, sid in enumerate(all_sids):
        exp = expectations[si]
        if not exp:
            continue
        ts, vals = db.read(b"default", sid, T0, t_end)
        got = dict(zip(np.asarray(ts).tolist(), np.asarray(vals).tolist()))
        for t, writes in exp.items():
            assert got.get(t) == writes[-1], (
                f"lost point {sid} t={t}: wrote {writes[-1]}, "
                f"read {got.get(t)}")
        total_checked += len(exp)
    assert total_checked > 1000, f"storm too small ({total_checked} points)"
