"""m3em remote operator transport (reference:
src/m3em/generated/proto/m3em.proto Operator service + m3em/agent): the
harness drives a per-host agent PROCESS over the operator RPC — setup with
config push, checksum-verified artifact transfer, start/stop/kill
lifecycle, heartbeats — and the agent manages the real service process."""

import hashlib
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from m3_tpu.em import EMCluster, ProcessSpec, RemoteOperator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def agent(tmp_path):
    """A REAL agent subprocess, as m3em deploys per host."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "m3_tpu.em", "--workdir", str(tmp_path / "w")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO)
    line = ""
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if "em agent listening on" in line:
            break
    else:
        raise TimeoutError("agent did not start")
    endpoint = line.rsplit(" ", 1)[-1].strip()
    yield endpoint, tmp_path
    proc.kill()
    proc.wait(timeout=10)


class TestRemoteOperator:
    def test_push_artifact_checksum_verified(self, agent):
        endpoint, tmp_path = agent
        op = RemoteOperator(endpoint)
        path = op.push_artifact("rules.yml", b"mapping: []\n")
        assert os.path.basename(path) == "rules.yml"
        # Corrupt digest is refused and the file is not left behind.
        with pytest.raises(RuntimeError, match="checksum"):
            op._request({"op": "push", "name": "bad.bin", "data": b"xyz",
                         "sha256": hashlib.sha256(b"other").hexdigest()})

    def test_full_lifecycle_through_agent(self, agent):
        endpoint, tmp_path = agent
        op = RemoteOperator(endpoint)
        workdir = str(tmp_path / "node")
        cfg = (
            "listen_address: 127.0.0.1:0\n"
            f"data_dir: {workdir}/data\n"
            "num_shards: 8\n"
            "coordinator:\n  namespace: default\n"
        )
        digest = op.setup(ProcessSpec("dbnode", cfg, workdir))
        assert digest == hashlib.sha256(cfg.encode()).hexdigest()
        assert not op.heartbeat()
        ep = op.start(timeout_s=60)
        assert op.heartbeat()
        assert ep.count(":") == 1
        op.kill()  # fault injection path
        assert not op.heartbeat()
        op.teardown()

    def test_emcluster_with_remote_node(self, agent, tmp_path):
        endpoint, agent_tmp = agent
        cluster = EMCluster(str(tmp_path / "em"))
        op = cluster.add_remote_node("node0", endpoint)
        try:
            eps = cluster.start_all()
            assert "node0" in eps
            assert cluster.alive() == {"node0": True}
        finally:
            cluster.teardown()
        assert cluster.operators == {}
        # Paths resolved agent-side: config landed in the AGENT's workdir,
        # not under the harness base_dir.
        assert os.path.exists(agent_tmp / "w" / "config.yml")
        assert not os.path.exists(tmp_path / "em" / "node0")

    def test_teardown_best_effort_past_unreachable_agent(self, tmp_path):
        """One dead agent must not leak the remaining nodes' processes."""
        cluster = EMCluster(str(tmp_path / "em"))
        cluster.operators["dead"] = RemoteOperator("127.0.0.1:1", timeout=0.5)
        local = cluster.add_node("local0")
        local.start(timeout_s=60)
        assert local.heartbeat()
        with pytest.raises(RuntimeError, match="dead"):
            cluster.teardown()
        assert cluster.operators == {}
        assert not local.heartbeat()
