"""Test harness config: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; all sharding/mesh tests run
against XLA's host-platform device emulation, which exercises the same
GSPMD partitioning and collective lowering paths (the driver separately
dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax

# The env var alone is NOT sufficient here: the axon TPU plugin registers
# itself regardless of JAX_PLATFORMS, so the config override is load-bearing
# (verified empirically — with only the env var, jax.devices() is the TPU).
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: spawns real service subprocesses")


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
