"""End-to-end ingest pipeline: single-chip step, sharded step over the 8-way
virtual mesh, and the driver graft entry points."""

import functools

import jax
import numpy as np

from m3_tpu.ops import tsz
from m3_tpu.parallel import ingest


def test_single_chip_ingest_roundtrip(rng):
    n, w = 32, 24
    batch = ingest.make_example_batch(n, w, rng)
    mw = tsz.max_words_for(w)
    words, nbits, roll, blk, qtl = jax.jit(
        functools.partial(ingest.ingest_step, rollup_factor=6, max_words=mw)
    )(batch)
    assert words.shape == (n, mw)
    assert np.asarray(roll["sum"]).shape == (n, w // 6)
    assert np.asarray(qtl).shape == (n, w // 6, 2)
    # Compressed streams must decode back to the exact input points.
    ts, vals = tsz.decode(np.asarray(words), np.full(n, w, np.int32), window=w)
    np.testing.assert_allclose(vals, np.asarray(batch.values, np.float64), rtol=1e-6)
    # Block stats match the rollup partials merged.
    np.testing.assert_allclose(
        np.asarray(blk["sum"]), np.asarray(roll["sum"]).sum(-1), rtol=1e-4
    )


def test_device_prep_matches_host_prep(rng):
    """prepare_on_device_math must reproduce the host prep bit-for-bit on
    live cells for k=0/float rows (decimal rows intentionally take the
    float path on device — DIVERGENCES.md)."""
    n, w = 256, 24
    raw_ts, raw_vals, npoints = ingest.make_example_raw(n, w, rng)
    npoints[:32] = rng.integers(1, w, 32)
    raw_vals[1, 2] = -0.0           # forces float mode
    raw_vals[2, 3] = np.nan
    raw_vals[3, :] = 2.0**52        # int-mode edge: still < 2^53
    raw_vals[4, :] = 2.0**53        # too big for the int path
    raw_vals[5, :] = -(2.0**52 + 1)
    raw_vals[6, :] = 0.25           # decimal: host k=2, device float mode
    host = tsz.prepare_encode_inputs(raw_ts, raw_vals, npoints)
    raw = ingest.make_raw_batch(raw_ts, raw_vals, npoints)
    hi, lo = ingest._HI, 1 - ingest._HI
    prep, ok = jax.jit(tsz.prepare_on_device_math)(
        raw.ts_pairs[..., hi], raw.ts_pairs[..., lo],
        raw.v_pairs[..., hi], raw.v_pairs[..., lo], raw.npoints)
    assert bool(ok)
    decimal = host["int_mode"] & (host["k"] > 0)
    assert decimal[6] and not bool(np.asarray(prep["int_mode"])[6])
    rows = ~decimal
    np.testing.assert_array_equal(
        np.asarray(prep["int_mode"])[rows], host["int_mode"][rows])
    for key in ("dt", "ts_regular", "delta0"):
        np.testing.assert_array_equal(np.asarray(prep[key]), host[key],
                                      err_msg=key)
    live = (np.arange(w)[None, :] < npoints[:, None]) & rows[:, None]
    np.testing.assert_array_equal(np.asarray(prep["vhi"])[live],
                                  host["vhi"][live])
    np.testing.assert_array_equal(np.asarray(prep["vlo"])[live],
                                  host["vlo"][live])


def test_raw_ingest_step_decodes_and_flags_range(rng):
    n, w = 64, 24
    raw_ts, raw_vals, npoints = ingest.make_example_raw(n, w, rng)
    mw = tsz.max_words_for(w)
    raw = ingest.make_raw_batch(raw_ts, raw_vals, npoints)
    out = jax.jit(functools.partial(
        ingest.ingest_step_raw, rollup_factor=6, max_words=mw))(raw)
    assert bool(out[-1])
    ts, vals = tsz.decode(np.asarray(out[0]), npoints, window=w)
    np.testing.assert_array_equal(ts, raw_ts)
    np.testing.assert_array_equal(vals, raw_vals)
    bad_ts = raw_ts.copy()
    bad_ts[0, 10] += 2**33  # delta overflows int32 ticks
    raw_bad = ingest.make_raw_batch(bad_ts, raw_vals, npoints)
    out_bad = jax.jit(functools.partial(
        ingest.ingest_step_raw, rollup_factor=6, max_words=mw))(raw_bad)
    assert not bool(out_bad[-1])


def test_sharded_ingest_on_virtual_mesh(rng):
    mesh = ingest.make_mesh(8)
    assert mesh.shape == {"shard": 4, "time": 2}
    t = mesh.shape["time"]
    n, w = 16, 12
    batch = ingest.make_example_batch(n, w, rng, chunks=t)
    sharded = ingest.shard_batch(batch, mesh)
    mw = tsz.max_words_for(w)
    step = ingest.make_sharded_ingest(mesh, rollup_factor=6, max_words=mw)
    words, nbits, roll, qtl, whole, total_bits = step(*sharded)
    assert words.shape == (t, n, mw)

    # Whole-window stats from collectives == host-side full-window reduction.
    flat_vals = np.concatenate([np.asarray(batch.values[i]) for i in range(t)], axis=1)
    np.testing.assert_allclose(np.asarray(whole["sum"]), flat_vals.sum(-1), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(whole["min"]), flat_vals.min(-1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(whole["max"]), flat_vals.max(-1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(whole["last"]), flat_vals[:, -1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(whole["first"]), flat_vals[:, 0], rtol=1e-6)
    assert int(total_bits) == int(np.asarray(nbits, np.int64).sum())

    # Every per-chunk stream decodes exactly.
    for i in range(t):
        ts, vals = tsz.decode(np.asarray(words[i]), np.full(n, w, np.int32), window=w)
        np.testing.assert_allclose(vals, np.asarray(batch.values[i], np.float64), rtol=1e-6)


class TestShardedServingPath:
    """The executor's mesh fast path (query/executor.py _eval_sharded_agg)
    must fire for dashboard-shaped aggregations on a multi-device platform
    and agree with the single-device evaluation."""

    def _engine(self, n=37, npts=48, mesh="auto"):
        from m3_tpu.query import Engine

        s_ns = 1_000_000_000
        rng = np.random.default_rng(5)
        t = 1_700_000_000 * s_ns + np.arange(npts, dtype=np.int64) * 10 * s_ns
        vals = np.cumsum(rng.poisson(3.0, (n, npts)), axis=1).astype(float)
        vals[rng.random((n, npts)) < 0.05] = np.nan
        series = {
            b"m{i=%d}" % i: {
                "tags": {b"__name__": b"m", b"i": str(i).encode()},
                "t": t, "v": vals[i]}
            for i in range(n)
        }

        class _S:
            def fetch_raw(self, matchers, start_ns, end_ns):
                return {k: dict(v) for k, v in series.items()}

        return Engine(_S(), mesh=mesh), int(t[12]), int(t[-1]), 30 * s_ns

    def test_sharded_agg_fires_and_matches_host(self):
        from m3_tpu.utils.instrument import ROOT

        eng, start, end, step = self._engine()
        eng_host, *_ = self._engine(mesh=None)
        assert eng.mesh is not None, "conftest provides 8 virtual devices"
        for q in ("sum(rate(m[1m]))", "avg(increase(m[1m]))",
                  "count(delta(m[1m]))", "max(rate(m[1m]))",
                  "min(rate(m[1m]))"):
            before = ROOT.counter("query.sharded_agg").value()
            got = eng.execute_range(q, start, end, step)
            assert ROOT.counter("query.sharded_agg").value() == before + 1, q
            want = eng_host.execute_range(q, start, end, step)
            assert got.n_series == want.n_series == 1
            np.testing.assert_allclose(got.values, want.values, rtol=1e-5,
                                       equal_nan=True, err_msg=q)

    def test_grouped_and_nonrate_fall_back_to_host(self):
        from m3_tpu.utils.instrument import ROOT

        eng, start, end, step = self._engine()
        before = ROOT.counter("query.sharded_agg").value()
        eng.execute_range("sum by (i) (rate(m[1m]))", start, end, step)
        eng.execute_range("sum(m)", start, end, step)
        assert ROOT.counter("query.sharded_agg").value() == before


def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


class TestShardedQuery:
    def test_sum_rate_matches_host(self):
        """Scatter-gather sum(rate(...)) over the virtual mesh equals the
        host executor's per-series rate + nansum."""
        import jax
        from m3_tpu.parallel import ingest as ing
        from m3_tpu.parallel import query as pq

        mesh = ing.make_mesh(8)
        S_, T, W = 37, 30, 6  # S deliberately not divisible by the axis
        rng = np.random.default_rng(4)
        grid = np.cumsum(rng.poisson(4.0, (S_, T)), axis=1).astype(np.float64)
        grid[rng.random((S_, T)) < 0.1] = np.nan
        step_ns, range_ns = 10 * 10**9, 60 * 10**9
        got = pq.sum_rate(grid, mesh, W=W, step_ns=step_ns, range_ns=range_ns)
        want = pq.sum_rate_host_reference(grid, W=W, step_ns=step_ns,
                                          range_ns=range_ns)
        np.testing.assert_allclose(got, want, rtol=1e-5, equal_nan=True)
