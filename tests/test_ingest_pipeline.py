"""End-to-end ingest pipeline: single-chip step, sharded step over the 8-way
virtual mesh, and the driver graft entry points."""

import functools

import jax
import numpy as np

from m3_tpu.ops import tsz
from m3_tpu.parallel import ingest


def test_single_chip_ingest_roundtrip(rng):
    n, w = 32, 24
    batch = ingest.make_example_batch(n, w, rng)
    mw = tsz.max_words_for(w)
    words, nbits, roll, blk, qtl = jax.jit(
        functools.partial(ingest.ingest_step, rollup_factor=6, max_words=mw)
    )(batch)
    assert words.shape == (n, mw)
    assert np.asarray(roll["sum"]).shape == (n, w // 6)
    assert np.asarray(qtl).shape == (n, w // 6, 2)
    # Compressed streams must decode back to the exact input points.
    ts, vals = tsz.decode(np.asarray(words), np.full(n, w, np.int32), window=w)
    np.testing.assert_allclose(vals, np.asarray(batch.values, np.float64), rtol=1e-6)
    # Block stats match the rollup partials merged.
    np.testing.assert_allclose(
        np.asarray(blk["sum"]), np.asarray(roll["sum"]).sum(-1), rtol=1e-4
    )


def test_sharded_ingest_on_virtual_mesh(rng):
    mesh = ingest.make_mesh(8)
    assert mesh.shape == {"shard": 4, "time": 2}
    t = mesh.shape["time"]
    n, w = 16, 12
    batch = ingest.make_example_batch(n, w, rng, chunks=t)
    sharded = ingest.shard_batch(batch, mesh)
    mw = tsz.max_words_for(w)
    step = ingest.make_sharded_ingest(mesh, rollup_factor=6, max_words=mw)
    words, nbits, roll, qtl, whole, total_bits = step(*sharded)
    assert words.shape == (t, n, mw)

    # Whole-window stats from collectives == host-side full-window reduction.
    flat_vals = np.concatenate([np.asarray(batch.values[i]) for i in range(t)], axis=1)
    np.testing.assert_allclose(np.asarray(whole["sum"]), flat_vals.sum(-1), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(whole["min"]), flat_vals.min(-1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(whole["max"]), flat_vals.max(-1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(whole["last"]), flat_vals[:, -1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(whole["first"]), flat_vals[:, 0], rtol=1e-6)
    assert int(total_bits) == int(np.asarray(nbits, np.int64).sum())

    # Every per-chunk stream decodes exactly.
    for i in range(t):
        ts, vals = tsz.decode(np.asarray(words[i]), np.full(n, w, np.int32), window=w)
        np.testing.assert_allclose(vals, np.asarray(batch.values[i], np.float64), rtol=1e-6)


def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


class TestShardedQuery:
    def test_sum_rate_matches_host(self):
        """Scatter-gather sum(rate(...)) over the virtual mesh equals the
        host executor's per-series rate + nansum."""
        import jax
        from m3_tpu.parallel import ingest as ing
        from m3_tpu.parallel import query as pq

        mesh = ing.make_mesh(8)
        S_, T, W = 37, 30, 6  # S deliberately not divisible by the axis
        rng = np.random.default_rng(4)
        grid = np.cumsum(rng.poisson(4.0, (S_, T)), axis=1).astype(np.float64)
        grid[rng.random((S_, T)) < 0.1] = np.nan
        step_ns, range_ns = 10 * 10**9, 60 * 10**9
        got = pq.sum_rate(grid, mesh, W=W, step_ns=step_ns, range_ns=range_ns)
        want = pq.sum_rate_host_reference(grid, W=W, step_ns=step_ns,
                                          range_ns=range_ns)
        np.testing.assert_allclose(got, want, rtol=1e-5, equal_nan=True)
