"""DTest scenarios: destructive cluster tests (reference:
src/cmd/tools/dtest/tests/{add_down_node_bring_up,replace_down_node,
remove_up_node,seeded_bootstrap}.go, driven by the m3em harness
cmd/tools/dtest/harness/harness.go:94). Here the in-process cluster
harness plays the environment manager."""

import numpy as np
import pytest

from m3_tpu.client.session import Session, SessionOptions
from m3_tpu.cluster.placement import Instance
from m3_tpu.index.namespace_index import NamespaceIndex
from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.rpc.node_server import NodeServer, NodeService
from m3_tpu.storage.bootstrap import BootstrapContext, BootstrapProcess
from m3_tpu.storage.database import Database
from m3_tpu.storage.namespace import NamespaceOptions
from m3_tpu.testing.cluster import ClusterHarness
from m3_tpu.utils import xtime

NS = b"default"
IDS = [b"dt.a", b"dt.b", b"dt.c", b"dt.d"]


@pytest.fixture
def cluster():
    h = ClusterHarness(n_nodes=3, replica_factor=3, num_shards=16,
                       ns_opts=NamespaceOptions(index_enabled=False))
    yield h
    h.close()


def _seed_and_seal(h, session):
    now = h.clock()
    ts = [now - i * xtime.SECOND for i in range(12)]
    for j, sid in enumerate(IDS):
        session.write_batch(NS, [sid] * 12, ts, np.arange(12.0) + 10 * j)
    h.clock.advance(2 * xtime.HOUR + 11 * xtime.MINUTE)
    h.tick_all()


def _verify_all(session, h, base=0.0):
    for j, sid in enumerate(IDS):
        t, v = session.fetch(NS, sid, 0, h.clock() + 1)
        assert len(t) == 12, sid
        np.testing.assert_array_equal(np.sort(v), np.arange(12.0) + 10 * j)


def _peer_bootstrap(db, session, placement):
    proc = BootstrapProcess(
        chain=("peers", "uninitialized_topology"),
        ctx=BootstrapContext(session=session, placement=placement))
    return proc.run(db)[NS]


class TestAddDownNodeBringUp:
    def test_scenario(self, cluster):
        """add_down_node_bring_up.go: add a node, take it down immediately,
        bring it back, peer-bootstrap it; cluster serves throughout."""
        session = Session(cluster.topology, SessionOptions(timeout_s=10))
        _seed_and_seal(cluster, session)
        _verify_all(session, cluster)
        node = cluster.add_node("node3")
        cluster.placement_svc.mark_instance_available("node3")
        cluster.stop_node("node3")
        _verify_all(session, cluster)  # quorum reads survive the down node
        # Bring it up: fresh server over the same db + peer bootstrap.
        node.server = NodeServer(NodeService(node.db)).start()
        cluster.placement_svc.get()  # refresh
        # Placement must route to the new endpoint.
        from m3_tpu.cluster.placement import ShardState

        p = cluster.placement_svc.get()
        p.instances["node3"].endpoint = node.endpoint
        cluster.placement_svc._put(p, p.version)
        res = _peer_bootstrap(node.db, session, cluster.placement_svc.get())
        assert res.unfulfilled.is_empty()
        node.db.mark_bootstrapped()
        session2 = Session(cluster.topology, SessionOptions(timeout_s=10))
        _verify_all(session2, cluster)
        session.close()
        session2.close()


class TestRemoveUpNode:
    def test_scenario(self):
        """remove_up_node.go: removing a healthy node keeps every series
        readable from the remaining replicas (needs nodes > RF so the
        placement can rebalance the leaver's shards)."""
        h = ClusterHarness(n_nodes=4, replica_factor=3, num_shards=16,
                           ns_opts=NamespaceOptions(index_enabled=False))
        try:
            session = Session(h.topology, SessionOptions(timeout_s=10))
            _seed_and_seal(h, session)
            h.remove_node("node2")
            session2 = Session(h.topology, SessionOptions(timeout_s=10))
            # Repeatedly: the leaver's shards now have an INITIALIZING
            # (empty, unbootstrapped) new owner; a read racing it must
            # NEVER accept its empty response over the data-holding
            # replicas (route_shard_readable excludes it — the flake
            # this loop would reproduce under owner-inclusive routing).
            for _ in range(10):
                _verify_all(session2, h)
            session.close()
            session2.close()
        finally:
            h.close()


class TestReplaceDownNode:
    def test_scenario(self, cluster):
        """replace_down_node.go: kill a node, replace it in the placement,
        peer-bootstrap the replacement, verify full data coverage."""
        session = Session(cluster.topology, SessionOptions(timeout_s=10))
        _seed_and_seal(cluster, session)
        cluster.stop_node("node1")
        replacement = cluster._make_node("node9")
        cluster.placement_svc.replace_instance(
            "node1", Instance(id="node9", endpoint=replacement.endpoint))
        del cluster.nodes["node1"]
        cluster.nodes["node9"] = replacement
        res = _peer_bootstrap(replacement.db, session,
                              cluster.placement_svc.get())
        assert res.unfulfilled.is_empty()
        replacement.db.mark_bootstrapped()
        cluster.placement_svc.mark_instance_available("node9")
        session2 = Session(cluster.topology, SessionOptions(timeout_s=10))
        _verify_all(session2, cluster)
        # The replacement itself holds blocks for its owned shards.
        held = sum(len(sh.blocks)
                   for sh in replacement.db.namespace(NS).shards.values())
        assert held > 0
        session.close()
        session2.close()


class TestSLOsUnderChurn:
    def test_macro_scenario(self):
        """The composed production story (ROADMAP item 3): seeded
        open-loop mixed-priority load + seeded faultnet chaos + live
        placement churn (add/remove/replace + repair) on an RF=3
        cluster, then hard SLO verification — zero lost acked writes,
        zero shed CRITICAL, bounded p99/queues, AVAILABLE placement,
        replica-consistent checksums. scripts/churn_smoke.py runs the
        bigger seeded instance as a check_all tier."""
        from m3_tpu.testing.scenario import (
            ChurnScenario,
            ChurnScenarioOptions,
        )

        sc = ChurnScenario(ChurnScenarioOptions(
            seed=13, duration_s=1.2, base_rate=40, n_series=32,
            num_shards=8))
        try:
            result = sc.verify(sc.run())
        finally:
            sc.close()
        # The run did real work end to end: churn ops all executed,
        # acked writes were verified, blocks compared replica-wide.
        assert len(result.churn_log) == len(sc.opts.churn_ops)
        assert result.verified_points > 0
        assert result.checksum_blocks_checked > 0
        assert result.report.select(kind="critical", outcome="ok")

    def test_ledger_unique_allocations(self):
        from m3_tpu.testing.scenario import WriteLedger

        led = WriteLedger(1000)
        seen = set()
        for _ in range(100):
            t, v = led.next_write(b"s")
            assert (t, v) not in seen
            seen.add((t, v))
        led.ack(b"s", *led.next_write(b"s"))
        assert led.total_acked() == 1
        assert set(led.acked()) == {b"s"}


class TestSeededBootstrap:
    def test_scenario(self, cluster):
        """seeded_bootstrap.go: a node restarted over seeded filesets
        bootstraps from the filesystem without touching peers."""
        session = Session(cluster.topology, SessionOptions(timeout_s=10))
        _seed_and_seal(cluster, session)
        node = cluster.nodes["node0"]
        assert node.db.flush(node.persist) > 0
        fresh = Database(ShardSet(cluster.num_shards), clock=cluster.clock)
        fresh.create_namespace(NS, cluster.ns_opts)
        proc = BootstrapProcess(
            chain=("filesystem", "uninitialized_topology"),
            ctx=BootstrapContext(persist=node.persist))
        res = proc.run(fresh)[NS]
        assert "filesystem" in res.claimed
        assert not res.claimed["filesystem"].is_empty()
        total_blocks = sum(
            len(sh.blocks) for sh in fresh.namespace(NS).shards.values())
        assert total_blocks > 0
        # Data matches what the original node serves for a sample series.
        sid = IDS[0]
        shard_id = fresh.shard_set.lookup(sid)
        if fresh.namespace(NS).shards[shard_id].registry.get(sid) is not None:
            t, v = fresh.read(NS, sid, 0, cluster.clock() + 1)
            t0, v0 = node.db.read(NS, sid, 0, cluster.clock() + 1)
            np.testing.assert_array_equal(v, v0)
        session.close()
