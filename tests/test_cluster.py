"""Cluster plane: KV store, placement algorithms, services/election,
topology (reference semantics from src/cluster and src/dbnode/topology)."""

import pytest

from m3_tpu.cluster import kv as kvmod
from m3_tpu.cluster.kv import FileStore, MemStore
from m3_tpu.cluster.placement import (
    Instance,
    PlacementService,
    ShardState,
    initial_placement,
)
from m3_tpu.cluster.services import (
    CampaignState,
    HeartbeatService,
    LeaderService,
    ServiceInstance,
    Services,
)
from m3_tpu.cluster.topology import (
    ConsistencyLevel,
    DynamicTopology,
    TopologyMap,
    required_acks,
)


def test_kv_versions_and_cas():
    s = MemStore()
    assert s.get("k") is None
    assert s.set("k", b"v1") == 1
    assert s.set("k", b"v2") == 2
    assert s.get("k").data == b"v2"
    with pytest.raises(ValueError):
        s.check_and_set("k", 1, b"v3")
    assert s.check_and_set("k", 2, b"v3") == 3
    with pytest.raises(KeyError):
        s.set_if_not_exists("k", b"x")


def test_kv_watch_and_callbacks():
    s = MemStore()
    w = s.watch("key")
    assert not w.wait(0.01)
    s.set("key", b"a")
    assert w.wait(0.5)
    seen = []
    s.on_change("key", lambda k, v: seen.append(v.data))
    assert seen == [b"a"]  # immediate delivery of current value
    s.set("key", b"b")
    assert seen == [b"a", b"b"]


def test_file_store_reload(tmp_path):
    path = str(tmp_path / "kv.json")
    s1 = FileStore(path)
    s1.set("a", b"hello")
    s2 = FileStore(path)
    assert s2.get("a").data == b"hello"


def insts(n):
    return [Instance(f"i{k}", f"host{k}:9000") for k in range(n)]


def test_initial_placement_balanced():
    p = initial_placement(insts(4), num_shards=64, replica_factor=3)
    p.validate()
    counts = [len(i.shards) for i in p.instances.values()]
    assert max(counts) - min(counts) <= 1
    assert sum(counts) == 64 * 3
    # No instance owns the same shard twice (structural) and replicas differ.
    for s in range(64):
        owners = {i.id for i in p.replicas_for(s)}
        assert len(owners) == 3


def test_placement_add_remove_replace():
    store = MemStore()
    svc = PlacementService(store)
    svc.init(insts(3), num_shards=30, replica_factor=3)

    p = svc.add_instance(Instance("i3", "host3:9000"))
    new = p.instances["i3"]
    assert all(a.state == ShardState.INITIALIZING and a.source_id for a in new.shards.values())
    # Receivers + leavers keep every shard at >= RF owners during the move.
    for s in range(30):
        assert len(p.replicas_for(s, states=tuple(ShardState))) >= 3

    p = svc.mark_instance_available("i3")
    assert all(a.state == ShardState.AVAILABLE for a in p.instances["i3"].shards.values())
    p.validate()

    before = set(svc.get().instances["i0"].shards)
    p = svc.replace_instance("i0", Instance("i9", "host9:9000"))
    assert "i0" not in p.instances
    # Replacement inherits the leaving instance's shards 1:1.
    assert set(p.instances["i9"].shards) == before
    assert all(a.source_id == "i0" for a in p.instances["i9"].shards.values())
    p = svc.mark_instance_available("i9")
    p.validate()

    p = svc.remove_instance("i9")
    assert "i9" not in p.instances
    p = svc.mark_instance_available("i1")
    p = svc.mark_instance_available("i2")
    p = svc.mark_instance_available("i3")
    p.validate()


def test_services_and_heartbeat():
    now = {"t": 0}
    store = MemStore()
    hb = HeartbeatService(store, ttl_ns=100, clock=lambda: now["t"])
    svcs = Services(store, hb)
    svcs.advertise("m3dbnode", ServiceInstance("a", "h1:9000"))
    svcs.advertise("m3dbnode", ServiceInstance("b", "h2:9000"))
    assert [i.instance_id for i in svcs.instances("m3dbnode")] == ["a", "b"]
    assert hb.alive_instances("m3dbnode") == ["a", "b"]
    now["t"] = 150
    hb.beat("m3dbnode", "b")
    assert hb.alive_instances("m3dbnode") == ["b"]
    svcs.unadvertise("m3dbnode", "a")
    assert [i.instance_id for i in svcs.instances("m3dbnode")] == ["b"]


def test_leader_election_failover():
    now = {"t": 0}
    store = MemStore()
    e1 = LeaderService(store, "agg", "node1", lease_ttl_ns=100, clock=lambda: now["t"])
    e2 = LeaderService(store, "agg", "node2", lease_ttl_ns=100, clock=lambda: now["t"])
    assert e1.campaign() == CampaignState.LEADER
    assert e2.campaign() == CampaignState.FOLLOWER
    assert e2.leader() == "node1"
    # Leader renews within TTL.
    now["t"] = 50
    assert e1.renew()
    now["t"] = 120
    assert e2.leader() == "node1"
    # Lease expires without renewal -> follower takes over.
    now["t"] = 200
    assert e2.campaign() == CampaignState.LEADER
    assert e1.campaign() == CampaignState.FOLLOWER
    # Resign releases immediately.
    e2.resign()
    assert e1.campaign() == CampaignState.LEADER


def test_topology_map_and_consistency():
    p = initial_placement(insts(3), num_shards=16, replica_factor=3)
    tm = TopologyMap(p)
    for s in range(16):
        assert len(tm.route_shard(s)) == 3
    assert tm.majority_replicas() == 2
    assert required_acks(ConsistencyLevel.ONE, 3) == 1
    assert required_acks(ConsistencyLevel.MAJORITY, 3) == 2
    assert required_acks(ConsistencyLevel.ALL, 3) == 3


def test_topology_readable_excludes_initializing():
    """Reads must not route to INITIALIZING owners: they have not
    bootstrapped, and a consistency-ONE read accepting their empty
    response silently loses the data the real replicas hold (the
    remove_up_node flake this pins). Writes still include them."""
    from m3_tpu.cluster.placement import ShardAssignment, ShardState

    p = initial_placement(insts(3), num_shards=4, replica_factor=2)
    # force shard 0's owner on the first instance into INITIALIZING
    first = sorted(p.instances)[0]
    inst = p.instances[first]
    owned = sorted(inst.shards)
    s0 = owned[0]
    inst.shards[s0] = ShardAssignment(s0, ShardState.INITIALIZING)
    tm = TopologyMap(p)
    writers = {h.id for h in tm.route_shard(s0)}
    readers = {h.id for h in tm.route_shard_readable(s0)}
    assert first in writers  # writes reach the bootstrapping owner
    assert first not in readers  # reads never see it
    assert readers  # the available replica still serves
    # all-initializing shard: degraded fallback serves the full set
    for iid, i in p.instances.items():
        for s, a in list(i.shards.items()):
            i.shards[s] = ShardAssignment(s, ShardState.INITIALIZING)
    tm2 = TopologyMap(p)
    assert tm2.route_shard_readable(s0) == tm2.route_shard(s0)


def test_dynamic_topology_reacts_to_placement_change():
    store = MemStore()
    svc = PlacementService(store)
    svc.init(insts(3), num_shards=8, replica_factor=2)
    topo = DynamicTopology(svc)
    seen = []
    topo.subscribe(lambda m: seen.append(len(m.hosts)))
    assert seen == [3]
    svc.add_instance(Instance("i3", "host3:9000"))
    assert seen[-1] == 4
    assert "i3" in topo.get().hosts
