"""m3lint: unit tests for every rule family on synthetic positive and
negative snippets, plus the tier-1 tree gate — `python -m
m3_tpu.analysis m3_tpu/` must report ZERO non-suppressed findings, so
any true positive a new rule finds must be fixed (or get a justified
suppression) in the same change that adds the rule."""

import pathlib
import subprocess
import sys
import textwrap

from m3_tpu.analysis import Module, all_rules, run_module, run_paths
from m3_tpu.analysis.batch_rules import BatchPartialIngestRule
from m3_tpu.analysis.cache_rules import (CacheKeyBufferRule,
                                         CacheMethodBufferKeyRule)
from m3_tpu.analysis.jax_rules import (ItemInLoopRule, JaxPurityRule,
                                       MeshSpecRule, NonStaticJitCacheRule,
                                       UnclassifiedDeviceDispatchRule,
                                       UnguardedPallasDispatchRule)
from m3_tpu.analysis.numeric_rules import (DtypeDataflowRule,
                                           SentinelTaintRule)
from m3_tpu.analysis.lock_rules import (FlushCallbackLoopRule,
                                        HotLoopUnderLockRule,
                                        LockDisciplineRule)
from m3_tpu.analysis.hbm_rules import UnbudgetedDevicePutRule
from m3_tpu.analysis.obs_rules import (HostSyncInPlanRule,
                                       UnboundedTelemetryTagRule,
                                       WallClockLatencyRule)
from m3_tpu.analysis.overload_rules import UnboundedQueueRule
from m3_tpu.analysis.replay_rules import PerEntryReplayRule
from m3_tpu.analysis.diskio_rules import UncheckedDiskIORule
from m3_tpu.analysis.retry_rules import (BroadExceptWireIORule,
                                         RawSleepRetryRule)

REPO = pathlib.Path(__file__).resolve().parent.parent


def lint(source, rule, relpath="m3_tpu/ops/mod.py"):
    """Non-suppressed findings of one rule over a source snippet."""
    mod = Module.from_source(textwrap.dedent(source), relpath)
    findings, _ = run_module(mod, [rule])
    return findings


def rule_ids(findings):
    return [f.rule for f in findings]


class TestCacheKeyBuffer:
    def test_flags_prefix_hashing_pattern(self):
        # the EXACT pre-fix m3_tpu/utils/hashing.py shape: lru_cache
        # wrapped around a bytes-annotated scalar hash
        src = """
            import functools

            def murmur3_32(data: bytes, seed: int = 0) -> int:
                return len(data)

            _murmur3_32_lru = functools.lru_cache(maxsize=65536)(murmur3_32)
        """
        found = lint(src, CacheKeyBufferRule(), "m3_tpu/utils/hashing.py")
        assert rule_ids(found) == ["cache-key-buffer"]
        assert "'data'" in found[0].message

    def test_flags_decorator_form_and_bytearray(self):
        src = """
            import functools

            @functools.lru_cache(maxsize=8)
            def route(key: bytearray) -> int:
                return len(key)
        """
        found = lint(src, CacheKeyBufferRule())
        assert rule_ids(found) == ["cache-key-buffer"]
        assert "bytearray" in found[0].message

    def test_flags_union_and_string_annotations(self):
        src = """
            from functools import lru_cache
            from typing import Union

            @lru_cache(maxsize=8)
            def f(x: "Union[bytes, memoryview]") -> int:
                return len(x)
        """
        assert rule_ids(lint(src, CacheKeyBufferRule())) == ["cache-key-buffer"]

    def test_infers_from_call_sites_when_unannotated(self):
        src = """
            import functools

            @functools.lru_cache(maxsize=8)
            def f(x):
                return len(x)

            def caller():
                return f(b"hot-id") + f(bytearray(3))
        """
        found = lint(src, CacheKeyBufferRule())
        assert rule_ids(found) == ["cache-key-buffer"]
        assert "call site" in found[0].message

    def test_clean_scalar_keys_pass(self):
        src = """
            import functools

            @functools.lru_cache(maxsize=8)
            def f(width: int, qs: tuple) -> int:
                return width

            @functools.lru_cache(maxsize=8)
            def g(name: str) -> str:
                return name

            def cache(x):
                return x

            cache(b"not-functools-cache")
        """
        assert lint(src, CacheKeyBufferRule()) == []

    def test_suppression_silences(self):
        src = """
            import functools

            def f(data: bytes) -> int:
                return len(data)

            g = functools.lru_cache(maxsize=8)(f)  # m3lint: disable=cache-key-buffer
        """
        assert lint(src, CacheKeyBufferRule()) == []


class TestCacheMethodBufferKey:
    """Custom-cache boundary: buffer params must be bytes-normalized
    before they reach a key (the PostingsListCache contract)."""

    def test_flags_raw_buffer_in_key_tuple(self):
        src = """
            class PostingsCache:
                def get(self, gen: int, field: bytes, key: bytes):
                    return self._lru.get((gen, field, key))
        """
        found = lint(src, CacheMethodBufferKeyRule())
        assert rule_ids(found) == ["cache-buffer-key-method"]
        assert "'field'" in found[0].message

    def test_flags_raw_subscript_and_memoryview(self):
        src = """
            class SegCache:
                def put(self, key: memoryview, value):
                    self._map[key] = value
        """
        assert rule_ids(lint(src, CacheMethodBufferKeyRule())) == [
            "cache-buffer-key-method"]

    def test_flags_map_get_arg(self):
        src = """
            class RouteCache:
                def lookup(self, key: bytearray):
                    return self._entries.get(key)
        """
        assert rule_ids(lint(src, CacheMethodBufferKeyRule())) == [
            "cache-buffer-key-method"]

    def test_rebind_normalization_passes(self):
        src = """
            class PostingsCache:
                def get(self, gen: int, field: bytes, key: bytes):
                    field = bytes(field)
                    key = bytes(key)
                    return self._lru.get((gen, field, key))
        """
        assert lint(src, CacheMethodBufferKeyRule()) == []

    def test_inline_bytes_wrap_passes(self):
        src = """
            class PostingsCache:
                @staticmethod
                def _key(gen: int, field: bytes, key: bytes):
                    return (gen, bytes(field), "term", bytes(key))
        """
        assert lint(src, CacheMethodBufferKeyRule()) == []

    def test_use_before_normalization_still_flagged(self):
        src = """
            class LateCache:
                def put(self, key: bytes, v):
                    self._map[key] = v
                    key = bytes(key)
        """
        assert rule_ids(lint(src, CacheMethodBufferKeyRule())) == [
            "cache-buffer-key-method"]

    def test_non_cache_class_and_scalar_params_ignored(self):
        src = """
            class Registry:
                def get(self, key: bytes):
                    return self._map.get(key)

            class WidthCache:
                def get(self, width: int, name: str):
                    return self._map.get((width, name))

                def helper(self, data: bytes):
                    return len(data)
        """
        assert lint(src, CacheMethodBufferKeyRule()) == []

    def test_delegating_to_normalizing_key_builder_passes(self):
        src = """
            class PostingsCache:
                @staticmethod
                def _key(field: bytes, key: bytes):
                    return (bytes(field), bytes(key))

                def get(self, field: bytes, key: bytes):
                    return self._lru.get(self._key(field, key))
        """
        assert lint(src, CacheMethodBufferKeyRule()) == []

    def test_suppression_silences(self):
        src = """
            class PinCache:
                def get(self, key: bytes):
                    return self._map.get(key)  # m3lint: disable=cache-buffer-key-method
        """
        assert lint(src, CacheMethodBufferKeyRule()) == []


class TestJaxPurity:
    def test_flags_branch_numpy_and_sync_in_jit(self):
        src = """
            import jax
            import numpy as np

            @jax.jit
            def f(x, y):
                if x > 0:
                    return np.sum(y)
                return float(y) + x.item()
        """
        ids = rule_ids(lint(src, JaxPurityRule()))
        assert ids.count("jax-traced-branch") == 1
        assert ids.count("jax-numpy-in-jit") == 1
        assert ids.count("jax-host-sync") == 2  # float() and .item()

    def test_static_argnames_and_is_none_are_fine(self):
        src = """
            import functools
            import jax
            import jax.numpy as jnp

            @functools.partial(jax.jit, static_argnames=("mode", "W"))
            def f(x, extra=None, *, mode, W):
                if mode:                    # static: trace-time constant
                    x = x * 2
                if extra is None:           # is-None: trace-time constant
                    extra = jnp.zeros(W)
                while x.shape[0] > 1:       # shapes are static metadata
                    x = x[:1]
                return x + extra
        """
        assert lint(src, JaxPurityRule()) == []

    def test_builder_idiom_closure_is_static(self):
        # the repo's lru_cache jit-builder: closure vars + Python loops
        # over static tuples are trace-time control flow, not violations
        src = """
            import functools
            import jax
            import jax.numpy as jnp

            @functools.lru_cache(maxsize=64)
            def builder(width: int, qs: tuple):
                def fn(values, counts):
                    mask = jnp.arange(width)[None, :] < counts[:, None]
                    outs = []
                    for q in qs:
                        outs.append(jnp.sum(jnp.where(mask, values, 0.0) * q))
                    return jnp.stack(outs)
                return jax.jit(fn)
        """
        assert lint(src, JaxPurityRule()) == []

    def test_taint_propagates_into_helpers(self):
        src = """
            import jax

            def _helper(v, n):
                if v.any():         # v arrives traced via the call below
                    return v
                return v * n

            @jax.jit
            def f(x):
                return _helper(x, 3)
        """
        found = lint(src, JaxPurityRule())
        assert rule_ids(found) == ["jax-traced-branch"]
        assert "_helper" in found[0].message

    def test_partial_bound_kwargs_are_static(self):
        src = """
            import functools
            import jax

            def rate_math(adj, finite, *, W, is_counter):
                if is_counter:      # partial-bound: static
                    adj = adj + 1
                return adj

            @functools.lru_cache(maxsize=256)
            def _rate_fn(W: int, is_counter: bool):
                return jax.jit(functools.partial(
                    rate_math, W=W, is_counter=is_counter))
        """
        assert lint(src, JaxPurityRule()) == []

    def test_nonstatic_jit_cache(self):
        src = """
            import functools
            import jax
            import jax.numpy as jnp

            @functools.lru_cache(maxsize=8)
            def builder(width: int, qs: list):
                return jax.jit(lambda v: jnp.sum(v) * width)
        """
        found = lint(src, NonStaticJitCacheRule())
        assert rule_ids(found) == ["jax-nonstatic-jit-cache"]
        assert "'qs'" in found[0].message

    def test_nonstatic_jit_cache_negative(self):
        src = """
            import functools
            import jax
            import jax.numpy as jnp

            @functools.lru_cache(maxsize=8)
            def builder(width: int, qs: tuple, flag: bool = False):
                return jax.jit(lambda v: jnp.sum(v) * width)

            @functools.lru_cache(maxsize=8)
            def not_a_builder(xs: list):
                return sum(xs)      # no jit inside: other rules' problem
        """
        assert lint(src, NonStaticJitCacheRule()) == []

    def test_item_in_loop(self):
        src = """
            import jax
            import numpy as np

            def drain(arrs):
                out = []
                for a in arrs:
                    out.append(a.item())
                return out

            def batched(arrs):
                return np.asarray(arrs)  # one transfer: fine
        """
        found = lint(src, ItemInLoopRule())
        assert rule_ids(found) == ["jax-item-in-loop"]
        assert found[0].severity == "warning"


class TestLockDiscipline:
    REL = "m3_tpu/storage/mod.py"

    def test_abba_inversion_direct_and_call_mediated(self):
        src = """
            import threading

            class T:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def ab(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def ba(self):
                    with self._b_lock:
                        self.take_a()

                def take_a(self):
                    with self._a_lock:
                        pass
        """
        found = lint(src, LockDisciplineRule(), self.REL)
        assert rule_ids(found) == ["lock-order-inversion"]
        assert "_a_lock" in found[0].message and "_b_lock" in found[0].message

    def test_single_order_is_fine(self):
        src = """
            import threading

            class T:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def ab(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def ab2(self):
                    with self._a_lock:
                        self.take_b()

                def take_b(self):
                    with self._b_lock:
                        pass
        """
        assert lint(src, LockDisciplineRule(), self.REL) == []

    def test_nonreentrant_reacquire(self):
        src = """
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
        """
        found = lint(src, LockDisciplineRule(), self.REL)
        assert rule_ids(found) == ["lock-order-inversion"]
        assert "self-deadlock" in found[0].message

    def test_rlock_reentry_is_fine(self):
        src = """
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
        """
        assert lint(src, LockDisciplineRule(), self.REL) == []

    def test_blocking_under_lock_direct_and_via_callee(self):
        src = """
            import threading
            import time

            class T:
                def __init__(self):
                    self._lock = threading.Lock()

                def naps(self):
                    with self._lock:
                        time.sleep(1)

                def indirect(self):
                    with self._lock:
                        self.do_io()

                def do_io(self):
                    self._sock.sendall(b"x")
        """
        found = lint(src, LockDisciplineRule(), self.REL)
        ids = rule_ids(found)
        assert ids == ["lock-held-blocking-call"] * 2
        assert any("time.sleep" in f.message for f in found)
        assert any("do_io" in f.message for f in found)

    def test_condition_wait_exempt_and_snapshot_pattern(self):
        src = """
            import threading
            import time

            class T:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition()
                    self._items = []

                def waiter(self):
                    with self._cond:
                        self._cond.wait()   # THE blocking-under-lock shape

                def snapshot_then_block(self):
                    with self._lock:
                        items = list(self._items)
                    time.sleep(0.1)         # lock already released
                    return items
        """
        assert lint(src, LockDisciplineRule(), self.REL) == []

    def test_queue_get_under_lock(self):
        src = """
            import queue
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def drain(self):
                    with self._lock:
                        return self._q.get()
        """
        found = lint(src, LockDisciplineRule(), self.REL)
        assert rule_ids(found) == ["lock-held-blocking-call"]
        # dict .get() is NOT blocking: no finding for plain mappings
        src_ok = """
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._map = {}

                def lookup(self, k):
                    with self._lock:
                        return self._map.get(k)
        """
        assert lint(src_ok, LockDisciplineRule(), self.REL) == []

    def test_out_of_scope_dirs_skipped(self):
        src = """
            import threading
            import time

            _lock = threading.Lock()

            def naps():
                with _lock:
                    time.sleep(1)
        """
        # query/ and parallel/ JOINED the scope in PR 12 (the plan
        # compiler's caches and the remote-storage exchange lock are the
        # locks the multi-host mesh work will contend); metrics/ stays out
        mod = Module.from_source(textwrap.dedent(src), "m3_tpu/metrics/mod.py")
        rule = LockDisciplineRule()
        assert not rule.applies(mod)
        for now_in in ("m3_tpu/query/mod.py", "m3_tpu/parallel/mod.py"):
            assert rule.applies(
                Module.from_source(textwrap.dedent(src), now_in))


class TestBatchPartialIngest:
    REL = "m3_tpu/aggregator/mod.py"

    PRE_FIX = """
        import numpy as np

        def dispatch_timed_batch(agg, e):
            ids, times, values = e["ids"], e["times"], e["values"]
            if not (len(ids) == len(times) == len(values)):
                raise ValueError("mismatch")
            if not all(isinstance(m, (bytes, bytearray)) for m in ids):
                raise ValueError("ids must be bytes")
            times = times.tolist() if hasattr(times, "tolist") else times
            values = values.tolist() if hasattr(values, "tolist") else values
            for mid, t, v in zip(ids, times, values):
                agg.add_timed(mid, t, v)
    """

    POST_FIX = """
        import numpy as np

        def dispatch_timed_batch(agg, e):
            ids, times, values = e["ids"], e["times"], e["values"]
            if not (len(ids) == len(times) == len(values)):
                raise ValueError("mismatch")
            if not all(isinstance(m, (bytes, bytearray)) for m in ids):
                raise ValueError("ids must be bytes")
            ids = [m if type(m) is bytes else bytes(m) for m in ids]
            times = np.asarray(times)
            values = np.asarray(values)
            if times.dtype.kind not in "iuf" or values.dtype.kind not in "iuf":
                raise ValueError("non-numeric")
            times = times.tolist()
            values = values.tolist()
            for mid, t, v in zip(ids, times, values):
                agg.add_timed(mid, t, v)
    """

    def test_flags_pre_fix_dispatch_pattern(self):
        found = lint(self.PRE_FIX, BatchPartialIngestRule(), self.REL)
        msgs = " | ".join(f.message for f in found)
        assert rule_ids(found) == ["batch-partial-ingest"] * 3
        assert "bytearray" in msgs            # ids admit unhashable buffers
        assert "'times'" in msgs and "'values'" in msgs  # unvalidated cols

    def test_post_fix_dispatch_is_clean(self):
        assert lint(self.POST_FIX, BatchPartialIngestRule(), self.REL) == []

    def test_bare_asarray_without_dtype_check_still_flags(self):
        # np.asarray(col) with NO dtype and NO dtype check silently
        # coerces a mixed column to strings — the hazard survives, so
        # deleting the dtype check must re-flag the columns
        src = self.POST_FIX.replace(
            '            if times.dtype.kind not in "iuf" or '
            'values.dtype.kind not in "iuf":\n'
            '                raise ValueError("non-numeric")\n', "")
        assert 'dtype.kind' not in src  # the replace really removed it
        found = lint(src, BatchPartialIngestRule(), self.REL)
        msgs = " | ".join(f.message for f in found)
        assert rule_ids(found) == ["batch-partial-ingest"] * 2
        assert "'times'" in msgs and "'values'" in msgs

    def test_annassign_rlock_reentry_is_fine(self):
        # RLock declared via ANNOTATED assignment must still register as
        # reentrant (was a false self-deadlock through the name heuristic)
        src = """
            import threading

            class T:
                def __init__(self):
                    self._lock: threading.RLock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
        """
        assert lint(src, LockDisciplineRule(),
                    "m3_tpu/storage/mod.py") == []

    def test_dirs_scoping_anchors_at_package_root(self):
        # ancestor directories named like scoped packages (a checkout at
        # /tmp/msg/...) must not trip directory-scoped rules on modules
        # the scope excludes
        src = "import threading\n"
        rule = LockDisciplineRule()
        assert not rule.applies(
            Module.from_source(src, "/tmp/msg/proj/m3_tpu/metrics/x.py"))
        assert rule.applies(
            Module.from_source(src, "/tmp/metrics/proj/m3_tpu/msg/x.py"))

    def test_no_contract_no_finding(self):
        # zip loops without a validate-then-iterate contract (no
        # isinstance validation) are not all-or-nothing promises
        src = """
            def plot(xs, ys):
                out = []
                for x, y in zip(xs, ys):
                    out.append(draw(x, y))
                return out
        """
        assert lint(src, BatchPartialIngestRule(), self.REL) == []


class TestSuppressionAndRunner:
    def test_line_and_next_line_and_file_suppression(self):
        base = """
            import functools

            @functools.lru_cache(maxsize=8){deco_comment}
            def f(data: bytes) -> int:
                return len(data)
        """
        flagged = lint(base.format(deco_comment=""), CacheKeyBufferRule())
        assert len(flagged) == 1
        line = flagged[0].line
        # trailing comment on the flagged line
        src = textwrap.dedent(base.format(deco_comment=""))
        lines = src.splitlines()
        lines[line - 1] += "  # m3lint: disable=cache-key-buffer"
        assert lint("\n".join(lines), CacheKeyBufferRule()) == []
        # standalone comment on the line above
        lines = src.splitlines()
        lines.insert(line - 1, "# m3lint: disable=cache-key-buffer")
        assert lint("\n".join(lines), CacheKeyBufferRule()) == []
        # file-level
        assert lint("# m3lint: disable-file=all\n" + src,
                    CacheKeyBufferRule()) == []

    def test_trailing_suppression_does_not_bleed_to_next_line(self):
        # a trailing disable on line N must NOT suppress a finding on
        # line N+1 — only STANDALONE comment lines cover the line below
        src = textwrap.dedent("""
            import functools

            def f(data: bytes) -> int:
                return len(data)

            g = functools.lru_cache(8)(f)  # m3lint: disable=cache-key-buffer
            h = functools.lru_cache(8)(f)
        """)
        found = lint(src, CacheKeyBufferRule())
        assert len(found) == 1  # only the unsuppressed wrap reports
        assert found[0].line == src.splitlines().index(
            "h = functools.lru_cache(8)(f)") + 1

    def test_overlapping_paths_analyze_each_file_once(self, tmp_path):
        f = tmp_path / "ops" / "one.py"
        f.parent.mkdir()
        f.write_text(textwrap.dedent("""
            import functools

            @functools.lru_cache(maxsize=8)
            def f(data: bytes) -> int:
                return len(data)
        """))
        findings, _, nmods = run_paths([str(tmp_path), str(f)])
        assert nmods == 1
        assert len(findings) == 1

    def test_disable_marker_in_string_is_not_honored(self):
        src = """
            import functools

            S = "# m3lint: disable-file=all"

            @functools.lru_cache(maxsize=8)
            def f(data: bytes) -> int:
                return len(data)
        """
        assert len(lint(src, CacheKeyBufferRule())) == 1

    def test_cli_exit_codes(self, tmp_path):
        bad = tmp_path / "ops" / "bad.py"
        bad.parent.mkdir()
        bad.write_text(textwrap.dedent("""
            import functools

            @functools.lru_cache(maxsize=8)
            def f(data: bytes) -> int:
                return len(data)
        """))
        env_dir = str(REPO)
        r = subprocess.run(
            [sys.executable, "-m", "m3_tpu.analysis", str(bad)],
            cwd=env_dir, capture_output=True, text=True)
        assert r.returncode == 1
        assert "cache-key-buffer" in r.stdout
        ok = tmp_path / "ops" / "ok.py"
        ok.write_text("x = 1\n")
        r = subprocess.run(
            [sys.executable, "-m", "m3_tpu.analysis", str(ok)],
            cwd=env_dir, capture_output=True, text=True)
        assert r.returncode == 0

    def test_unparseable_file_is_a_finding(self, tmp_path):
        f = tmp_path / "broken.py"
        f.write_text("def f(:\n")
        findings, _, _ = run_paths([str(f)])
        assert rule_ids(findings) == ["parse-error"]


class TestRetryRules:
    def test_flags_fixed_delay_retry_loop(self):
        src = """
            import time

            def pump(connect):
                while True:
                    try:
                        connect()
                        return
                    except OSError:
                        pass
                    time.sleep(0.2)
        """
        found = lint(src, RawSleepRetryRule(), "m3_tpu/msg/mod.py")
        assert rule_ids(found) == ["raw-sleep-retry"]

    def test_sleep_in_handler_also_flags(self):
        src = """
            import time

            def fetch(call):
                for _ in range(5):
                    try:
                        return call()
                    except ConnectionError:
                        time.sleep(1.0)
        """
        assert rule_ids(lint(src, RawSleepRetryRule())) == ["raw-sleep-retry"]

    def test_poll_loop_without_try_is_fine(self):
        src = """
            import time

            def watch(poll):
                while True:
                    poll()
                    time.sleep(5)
        """
        assert lint(src, RawSleepRetryRule()) == []

    def test_retrier_module_is_exempt(self):
        src = """
            import time

            def attempt(fn):
                while True:
                    try:
                        return fn()
                    except OSError:
                        time.sleep(0.1)
        """
        assert lint(src, RawSleepRetryRule(), "m3_tpu/utils/retry.py") == []
        # ...but the same shape anywhere else is not
        assert lint(src, RawSleepRetryRule(), "m3_tpu/cluster/mod.py")

    def test_nested_function_sleep_not_attributed_to_loop(self):
        src = """
            import time

            def outer(items):
                while items:
                    try:
                        items.pop()
                    except IndexError:
                        pass

                    def helper():
                        time.sleep(1)
        """
        assert lint(src, RawSleepRetryRule()) == []

    def test_flags_broad_except_around_wire_io(self):
        src = """
            from ..rpc import wire

            def serve(sock):
                try:
                    return wire.read_frame(sock)
                except Exception:
                    return None
        """
        found = lint(src, BroadExceptWireIORule(), "m3_tpu/query/mod.py")
        assert rule_ids(found) == ["broad-except-wire-io"]
        assert "read_frame" in found[0].message

    def test_bare_except_and_write_frame_flag(self):
        src = """
            from ..rpc import wire

            def push(sock, v):
                try:
                    wire.write_frame(sock, v)
                except:
                    pass
        """
        assert rule_ids(lint(src, BroadExceptWireIORule())) == \
            ["broad-except-wire-io"]

    def test_typed_except_set_is_fine(self):
        src = """
            from ..rpc import wire

            def serve(sock):
                try:
                    while True:
                        wire.write_frame(sock, wire.read_dict_frame(sock))
                except (ConnectionError, OSError, ValueError):
                    pass
        """
        assert lint(src, BroadExceptWireIORule()) == []

    def test_inner_typed_try_owns_its_wire_calls(self):
        # the node_server shape: a broad handler for DISPATCH errors is
        # fine when the wire I/O has its own typed containment
        src = """
            from ..rpc import wire

            def handle(sock, dispatch):
                try:
                    while True:
                        try:
                            req = wire.read_dict_frame(sock)
                        except (ConnectionError, ValueError):
                            return
                        dispatch(req)
                except Exception:
                    pass
        """
        assert lint(src, BroadExceptWireIORule()) == []

    def test_broad_except_without_wire_io_is_out_of_scope(self):
        src = """
            def run(fn):
                try:
                    return fn()
                except Exception:
                    return None
        """
        assert lint(src, BroadExceptWireIORule()) == []

    def test_flags_broad_except_around_peer_streaming_in_bootstrap(self):
        # the pre-fix PeersBootstrapper.bootstrap hole: peers unavailable
        # silently claimed nothing
        src = """
            def bootstrap(ns, shard_id, ctx):
                try:
                    series = ctx.session.fetch_bootstrap_blocks_from_peers(
                        ns.name, shard_id, 0, 1)
                except Exception:
                    return None
        """
        found = lint(src, BroadExceptWireIORule(),
                     "m3_tpu/storage/bootstrap.py")
        assert rule_ids(found) == ["broad-except-wire-io"]
        assert "peer-streaming" in found[0].message

    def test_flags_broad_except_around_tile_fetch_in_repair(self):
        src = """
            def sweep(self, ns, shard_id, plan):
                try:
                    tiles, failed = self.session.fetch_block_tiles(
                        ns.name, shard_id, plan)
                except Exception:
                    tiles, failed = {}, []
                return tiles
        """
        assert rule_ids(lint(src, BroadExceptWireIORule(),
                             "m3_tpu/storage/repair.py")) == \
            ["broad-except-wire-io"]

    def test_peer_streaming_scope_covers_query_and_parallel(self):
        # PR 12 widened the peer-I/O treatment to query/ and parallel/
        # (remote fan-ins are wire I/O one hop removed there too); the
        # same shape in e.g. coordinator/ stays out of this extension
        src = """
            def mirror(session, ns):
                try:
                    return session.fetch_bootstrap_blocks_from_peers(
                        ns, 0, 0, 1)
                except Exception:
                    return {}
        """
        found = lint(src, BroadExceptWireIORule(), "m3_tpu/query/mod.py")
        assert rule_ids(found) == ["broad-except-wire-io"]
        assert lint(src, BroadExceptWireIORule(),
                    "m3_tpu/coordinator/mod.py") == []

    def test_broad_handler_with_bare_reraise_is_exempt(self):
        # settle-the-grant-then-raise (query/remote._exchange): a broad
        # handler ending in a bare re-raise FORWARDS the typed
        # classification — nothing is eaten
        src = """
            from . import wire

            def exchange(sock, req):
                try:
                    wire.write_frame(sock, req)
                except BaseException:
                    req["breaker"].record_failure()
                    raise
        """
        assert lint(src, BroadExceptWireIORule(),
                    "m3_tpu/rpc/mod.py") == []

    def test_broad_handler_with_escaping_branch_still_flags(self):
        # the bare-raise exemption requires forwarding on EVERY path: an
        # early return inside the handler swallows the classification
        src = """
            from . import wire

            def exchange(sock, req, transient):
                try:
                    wire.write_frame(sock, req)
                except Exception:
                    if transient:
                        return None
                    raise
        """
        found = lint(src, BroadExceptWireIORule(), "m3_tpu/rpc/mod.py")
        assert rule_ids(found) == ["broad-except-wire-io"]

    def test_loop_local_break_does_not_void_the_reraise_exemption(self):
        # break/continue bound to a loop INSIDE the handler never leave
        # the handler — the final bare raise still runs on every path
        src = """
            from . import wire

            def exchange(sock, req, attempts):
                try:
                    wire.write_frame(sock, req)
                except Exception:
                    for a in attempts:
                        if a.stale():
                            continue
                        a.cancel()
                        break
                    raise
        """
        assert lint(src, BroadExceptWireIORule(),
                    "m3_tpu/rpc/mod.py") == []

    def test_typed_peer_skip_set_is_fine_in_bootstrap(self):
        # the post-fix shape: typed classification, counted skip
        src = """
            from ..client.session import PEER_SKIP_ERRORS

            def bootstrap(ns, shard_id, ctx):
                try:
                    tiles, tags, failed = \\
                        ctx.session.fetch_block_tiles_from_peers(
                            ns.name, shard_id, 0, 1)
                except PEER_SKIP_ERRORS:
                    return None
        """
        assert lint(src, BroadExceptWireIORule(),
                    "m3_tpu/storage/bootstrap.py") == []

    def test_suppression_silences_with_justification(self):
        src = """
            from ..rpc import wire

            def relay(sock, work):
                try:
                    wire.write_frame(sock, work())
                # DELIBERATE: error-relay contract
                except Exception:  # m3lint: disable=broad-except-wire-io
                    pass
        """
        assert lint(src, BroadExceptWireIORule()) == []


class TestUnboundedQueueRule:
    """unbounded-queue: stdlib Queue()/deque() without a bound inside the
    buffering layers (storage/msg/coordinator/aggregator/rpc) turn
    overload into OOM instead of backpressure."""

    def test_flags_unbounded_deque_in_msg(self):
        src = """
            from collections import deque

            pending = deque()
        """
        found = lint(src, UnboundedQueueRule(), "m3_tpu/msg/mod.py")
        assert rule_ids(found) == ["unbounded-queue"]

    def test_flags_unbounded_queue_in_storage(self):
        src = """
            import queue

            work = queue.Queue()
        """
        found = lint(src, UnboundedQueueRule(), "m3_tpu/storage/mod.py")
        assert rule_ids(found) == ["unbounded-queue"]

    def test_flags_literal_unbounded_maxsize(self):
        # Queue semantics: maxsize <= 0 means infinite — a literal 0 or
        # negative bound is no bound
        src = """
            import queue

            a = queue.Queue(0)
            b = queue.Queue(maxsize=-1)
        """
        found = lint(src, UnboundedQueueRule(), "m3_tpu/rpc/mod.py")
        assert rule_ids(found) == ["unbounded-queue", "unbounded-queue"]

    def test_simple_queue_always_flags(self):
        src = """
            import queue

            q = queue.SimpleQueue()
        """
        found = lint(src, UnboundedQueueRule(), "m3_tpu/aggregator/mod.py")
        assert rule_ids(found) == ["unbounded-queue"]
        assert "no capacity bound" in found[0].message

    def test_bounded_forms_are_fine(self):
        src = """
            import queue
            from collections import deque

            a = queue.Queue(100)
            b = queue.Queue(maxsize=64)
            c = deque(maxlen=4096)
            d = deque([], 16)
        """
        assert lint(src, UnboundedQueueRule(), "m3_tpu/msg/mod.py") == []

    def test_out_of_scope_dirs_are_ignored(self):
        src = """
            from collections import deque

            scratch = deque()
        """
        assert lint(src, UnboundedQueueRule(), "m3_tpu/ops/mod.py") == []

    def test_local_helper_named_deque_is_not_stdlib(self):
        src = """
            def deque():
                return []

            pending = deque()
        """
        assert lint(src, UnboundedQueueRule(), "m3_tpu/msg/mod.py") == []

    def test_dotted_non_stdlib_parent_is_ignored(self):
        src = """
            import mylib

            q = mylib.Queue()
        """
        assert lint(src, UnboundedQueueRule(), "m3_tpu/msg/mod.py") == []

    def test_suppression_with_justification(self):
        src = """
            from collections import deque

            # DELIBERATE: control-plane only, bounded by topic count
            topics = deque()  # m3lint: disable=unbounded-queue
        """
        assert lint(src, UnboundedQueueRule(), "m3_tpu/msg/mod.py") == []


class TestUnbudgetedDevicePut:
    """unbudgeted-device-put: raw jax.device_put on the storage/query
    serving path pins HBM the shared budget (utils/hbm.py) can't see."""

    def test_flags_dotted_call_in_storage(self):
        src = """
            import jax

            dev = jax.device_put(words)
        """
        found = lint(src, UnbudgetedDevicePutRule(),
                     "m3_tpu/storage/mod.py")
        assert rule_ids(found) == ["unbudgeted-device-put"]

    def test_flags_from_import_form_in_query(self):
        src = """
            import jax
            from jax import device_put

            arr = device_put(grid, dev)
        """
        found = lint(src, UnbudgetedDevicePutRule(), "m3_tpu/query/mod.py")
        assert rule_ids(found) == ["unbudgeted-device-put"]

    def test_flags_module_level_alias(self):
        # the encode_prepared staging idiom: put = jax.device_put
        src = """
            import jax

            put = jax.device_put
            a = put(x, sharding)
            b = put(y, sharding)
        """
        found = lint(src, UnbudgetedDevicePutRule(), "m3_tpu/ops/mod.py")
        assert rule_ids(found) == ["unbudgeted-device-put"] * 2

    def test_budgeted_put_is_fine(self):
        src = """
            import jax
            from m3_tpu.utils import hbm

            dev = hbm.budgeted_put(words)
        """
        assert lint(src, UnbudgetedDevicePutRule(),
                    "m3_tpu/storage/mod.py") == []

    def test_out_of_scope_dirs_are_ignored(self):
        src = """
            import jax

            dev = jax.device_put(frame)
        """
        assert lint(src, UnbudgetedDevicePutRule(),
                    "m3_tpu/testing/mod.py") == []

    def test_module_without_jax_import_is_skipped(self):
        src = """
            def device_put(x):
                return x

            dev = device_put(words)
        """
        assert lint(src, UnbudgetedDevicePutRule(),
                    "m3_tpu/storage/mod.py") == []

    def test_local_name_is_not_jax_device_put(self):
        # jax imported, but the called name is a local helper
        src = """
            import jax

            def device_put(x):
                return x

            dev = device_put(words)
        """
        assert lint(src, UnbudgetedDevicePutRule(),
                    "m3_tpu/storage/mod.py") == []

    def test_suppression_with_justification(self):
        src = """
            import jax

            # DELIBERATE: mesh-flush staging, freed when encode returns
            dev = jax.device_put(tile, sharding)  # m3lint: disable=unbudgeted-device-put
        """
        assert lint(src, UnbudgetedDevicePutRule(),
                    "m3_tpu/storage/mod.py") == []


class TestHotLoopUnderLock:
    """hot-loop-under-lock: per-item dict-mutation loops inside a
    `with <lock>` block in the storage/index/aggregator write paths —
    the shape the insert-queue rebuild removed from Shard.write_batch."""

    PRE_CHANGE_WRITE_BATCH = """
        import threading

        class Shard:
            def __init__(self):
                self.write_lock = threading.RLock()

            def write_batch(self, ids, ts, vals, tags):
                with self.write_lock:
                    for i, sid in enumerate(ids):
                        idx, is_new = self.registry.get_or_create(
                            sid, tags[i] if tags else None)
                        if is_new and self.on_new_series is not None:
                            self.on_new_series(sid, tags[i], idx)
                    self.buffer.write_batch(ids, ts, vals)
    """

    def test_flags_the_pre_change_shard_write_batch(self):
        # The seeded true positive: the EXACT pre-rebuild write path.
        found = lint(self.PRE_CHANGE_WRITE_BATCH, HotLoopUnderLockRule(),
                     "m3_tpu/storage/shard.py")
        assert rule_ids(found) == ["hot-loop-under-lock"]
        assert "get_or_create" in found[0].message

    def test_flags_setdefault_and_insert_loops(self):
        src = """
            import threading

            class Index:
                def __init__(self):
                    self._lock = threading.Lock()

                def insert_all(self, items, docs):
                    with self._lock:
                        for sid, tags in items:
                            self._terms.setdefault(sid, []).append(tags)
                        i = 0
                        while i < len(docs):
                            self.mutable.insert(docs[i])
                            i += 1
        """
        found = lint(src, HotLoopUnderLockRule(), "m3_tpu/index/mod.py")
        assert rule_ids(found) == ["hot-loop-under-lock"] * 2

    def test_batched_entrypoints_under_lock_are_fine(self):
        # The post-rebuild shape: one bulk apply per lock hold.
        src = """
            import threading

            class Shard:
                def __init__(self):
                    self.write_lock = threading.Lock()

                def drain(self, groups):
                    with self.write_lock:
                        for g in groups:
                            idxs, created = \\
                                self.registry.get_or_create_batch_tagged(
                                    g.ids, g.tags)
                            self.buffer.write_batch(idxs, g.ts, g.vals)

                def index_drain(self, docs):
                    with self._lock:
                        self.mutable.insert_batch(docs)
        """
        assert lint(src, HotLoopUnderLockRule(),
                    "m3_tpu/storage/shard.py") == []

    def test_loop_outside_lock_is_fine(self):
        src = """
            import threading

            class Shard:
                def __init__(self):
                    self.write_lock = threading.Lock()

                def write_batch(self, ids):
                    entries = []
                    for sid in ids:
                        entries.append(self.groups.setdefault(sid, []))
                    with self.write_lock:
                        self.buffer.write_batch(entries)
        """
        assert lint(src, HotLoopUnderLockRule(),
                    "m3_tpu/storage/shard.py") == []

    def test_nested_function_under_lock_not_attributed(self):
        src = """
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                def setup(self):
                    with self._lock:
                        def later(items):
                            for it in items:
                                self.m.insert(it)
                        self.cb = later
        """
        assert lint(src, HotLoopUnderLockRule(),
                    "m3_tpu/storage/mod.py") == []

    def test_out_of_scope_dirs_are_ignored(self):
        found = lint(self.PRE_CHANGE_WRITE_BATCH, HotLoopUnderLockRule(),
                     "m3_tpu/query/mod.py")
        assert found == []

    def test_suppression_with_justification(self):
        src = """
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                def rebuild(self, items):
                    with self._lock:
                        for it in items:
                            # DELIBERATE: cold recovery path, runs at boot
                            self.map.insert(it)  # m3lint: disable=hot-loop-under-lock
        """
        assert lint(src, HotLoopUnderLockRule(),
                    "m3_tpu/storage/mod.py") == []


class TestObsRules:
    # the EXACT pre-fix rpc/node_server.py shape: uptime measured as a
    # wall-clock delta across methods (assignment in __init__, the
    # subtraction in a handler) — the rule's seeded positive.
    PRE_FIX_UPTIME = """
        import time

        class NodeService:
            def __init__(self):
                self.start_ns = time.time_ns()

            def rpc_health(self):
                return {"uptime_ns": time.time_ns() - self.start_ns}
    """

    def test_flags_pre_fix_uptime_pattern(self):
        found = lint(self.PRE_FIX_UPTIME, WallClockLatencyRule(),
                     "m3_tpu/rpc/mod.py")
        assert rule_ids(found) == ["wall-clock-latency"]

    def test_flags_direct_latency_delta(self):
        src = """
            import time

            def handle(fn):
                t0 = time.time()
                fn()
                return time.time() - t0
        """
        found = lint(src, WallClockLatencyRule(), "m3_tpu/storage/mod.py")
        assert rule_ids(found) == ["wall-clock-latency"]

    def test_flags_bare_import_form(self):
        src = """
            from time import time

            def measure(fn):
                start = time()
                fn()
                return time() - start
        """
        found = lint(src, WallClockLatencyRule(), "m3_tpu/msg/mod.py")
        assert rule_ids(found) == ["wall-clock-latency"]

    def test_perf_counter_delta_is_fine(self):
        src = """
            import time

            def handle(fn):
                t0 = time.perf_counter()
                fn()
                return time.perf_counter() - t0
        """
        assert lint(src, WallClockLatencyRule(),
                    "m3_tpu/storage/mod.py") == []

    def test_wall_reads_and_range_arithmetic_are_fine(self):
        # data timestamps and range math read the wall clock without
        # measuring elapsed time: a single wall operand never flags.
        src = """
            import time

            def default_range(window_s):
                end = time.time()
                start = end - window_s
                return start, end

            def stamp():
                return time.time_ns()
        """
        assert lint(src, WallClockLatencyRule(),
                    "m3_tpu/query/mod.py") == []

    def test_out_of_scope_dirs_skipped(self):
        found = lint(self.PRE_FIX_UPTIME, WallClockLatencyRule(),
                     "m3_tpu/coordinator/mod.py")
        assert found == []

    def test_suppression_silences(self):
        src = """
            import time

            def handle(fn):
                t0 = time.time()
                fn()
                # DELIBERATE: test fixture comparing against wall stamps
                return time.time() - t0  # m3lint: disable=wall-clock-latency
        """
        assert lint(src, WallClockLatencyRule(),
                    "m3_tpu/storage/mod.py") == []


class TestHostSyncInPlan:
    # The pre-change per-op dispatch shape, transplanted into a lowering
    # rule: dispatch a kernel, np.asarray the result to the host, feed
    # the next operator — the round trip the whole-plan compiler removes.
    PRE_CHANGE_DISPATCH = """
        import numpy as np

        def _lower_rangefunc(ctx, node):
            out = ctx.kernel(ctx.grid)
            host = np.asarray(out)        # per-op host round trip
            return ctx.next_op(host)
    """

    def test_flags_pre_change_per_op_dispatch(self):
        found = lint(self.PRE_CHANGE_DISPATCH, HostSyncInPlanRule(),
                     "m3_tpu/parallel/compile.py")
        assert rule_ids(found) == ["host-sync-in-plan"]
        assert "np.asarray" in found[0].message

    def test_flags_item_in_emit(self):
        src = """
            def _emit(ctx, node):
                val = ctx.cache[id(node)]
                if val.sum().item() > 0:   # traced-value host sync
                    return val
                return -val
        """
        found = lint(src, HostSyncInPlanRule(), "m3_tpu/parallel/compile.py")
        assert rule_ids(found) == ["host-sync-in-plan"]
        assert ".item()" in found[0].message

    def test_flags_device_get_in_traced_body(self):
        src = """
            import jax

            def _plan_executable(stripped, geom):
                def body(fetch_flat, slots):
                    mid = jax.device_get(fetch_flat[0])
                    return mid + slots
                return jax.jit(body)
        """
        found = lint(src, HostSyncInPlanRule(), "m3_tpu/parallel/compile.py")
        assert rule_ids(found) == ["host-sync-in-plan"]

    def test_flags_bare_from_import(self):
        src = """
            from numpy import asarray

            def _lower_aggregate(ctx, node):
                return asarray(ctx.cache[id(node)])
        """
        found = lint(src, HostSyncInPlanRule(), "m3_tpu/parallel/compile.py")
        assert rule_ids(found) == ["host-sync-in-plan"]

    def test_host_finish_in_execute_is_fine(self):
        # execute() materializes AFTER the compiled program returns —
        # the legitimate sync point, outside the lowering surface.
        src = """
            import numpy as np

            def execute(bound, mesh):
                root_val = dispatch(bound)
                return np.asarray(root_val)[:4]
        """
        assert lint(src, HostSyncInPlanRule(),
                    "m3_tpu/parallel/compile.py") == []

    def test_other_parallel_modules_skipped(self):
        found = lint(self.PRE_CHANGE_DISPATCH, HostSyncInPlanRule(),
                     "m3_tpu/parallel/query.py")
        assert found == []

    def test_suppression_silences(self):
        src = """
            import numpy as np

            def _lower_fetch(ctx, node):
                # DELIBERATE: static bind-time constant, not a traced value
                shape = np.asarray(node.shape)  # m3lint: disable=host-sync-in-plan
                return ctx.fetch_ins[node][: shape[0]]
        """
        assert lint(src, HostSyncInPlanRule(),
                    "m3_tpu/parallel/compile.py") == []


class TestUnboundedTelemetryTag:
    # The seeded positive: the explain work's easy mistake — tagging the
    # plan-fallback counter with the raw query string mints one registry
    # entry (and one self-scraped series) per distinct query, forever.
    SEEDED_POSITIVE = """
        from m3_tpu.utils.instrument import ROOT

        def record_fallback(query, reason):
            ROOT.sub_scope("plan_fallback", query=query).counter("n").inc()
    """

    def test_flags_seeded_positive_query_tag(self):
        found = lint(self.SEEDED_POSITIVE, UnboundedTelemetryTagRule(),
                     "m3_tpu/query/mod.py")
        assert rule_ids(found) == ["unbounded-telemetry-tag"]
        assert "query" in found[0].message

    def test_flags_fstring_metric_name(self):
        src = """
            from m3_tpu.utils.instrument import ROOT

            def count(expr):
                ROOT.counter(f"fallback.{expr}").inc()
        """
        found = lint(src, UnboundedTelemetryTagRule(), "m3_tpu/query/mod.py")
        assert rule_ids(found) == ["unbounded-telemetry-tag"]

    def test_flags_str_wrapped_selector_tag_value(self):
        src = """
            from m3_tpu.utils.instrument import ROOT

            def record(selector):
                scope = ROOT.sub_scope("fetch", kind=str(selector))
                scope.counter("n").inc()
        """
        found = lint(src, UnboundedTelemetryTagRule(), "m3_tpu/query/mod.py")
        assert rule_ids(found) == ["unbounded-telemetry-tag"]

    def test_flags_percent_format_sub_scope_name(self):
        src = """
            from m3_tpu.utils.instrument import ROOT

            def record(pattern):
                ROOT.sub_scope("regexp.%s" % pattern).counter("n").inc()
        """
        found = lint(src, UnboundedTelemetryTagRule(), "m3_tpu/index/mod.py")
        assert rule_ids(found) == ["unbounded-telemetry-tag"]

    def test_closed_set_enum_value_is_fine(self):
        # The shipped shape: the FallbackReason enum VALUE is a closed
        # set — `reason` is not in the unbounded vocabulary.
        src = """
            from m3_tpu.utils.instrument import ROOT

            def plan_fallback(reason):
                ROOT.sub_scope("plan_fallback",
                               reason=reason).counter("count").inc()
        """
        assert lint(src, UnboundedTelemetryTagRule(),
                    "m3_tpu/parallel/mod.py") == []

    def test_bounded_builder_and_kind_interpolations_are_fine(self):
        # telemetry.py / limits.py house shapes: builder names, limit
        # kinds, admission-gate names — all closed sets.
        src = """
            from m3_tpu.utils.instrument import ROOT

            def jit_builder(name, kind):
                ROOT.sub_scope("jit", builder=name).counter("hits").inc()
                ROOT.counter(f"{kind}.exceeded").inc()
                ROOT.sub_scope(f"admission.{name}").gauge("depth")
        """
        assert lint(src, UnboundedTelemetryTagRule(),
                    "m3_tpu/utils/mod.py") == []

    def test_literal_names_and_tags_are_fine(self):
        src = """
            from m3_tpu.utils.instrument import ROOT

            SCOPE = ROOT.sub_scope("telemetry")

            def count():
                SCOPE.sub_scope("mesh", kernel="flush").counter("n").inc()
                SCOPE.histogram("compile_s", (0.1, 1.0)).record(0.5)
        """
        assert lint(src, UnboundedTelemetryTagRule(),
                    "m3_tpu/parallel/mod.py") == []

    def test_non_scope_calls_ignored(self):
        # dict.get / collections.Counter / unrelated .counter-free calls
        # never match; only scope-method shapes do.
        src = """
            import collections

            def tally(query, counts):
                c = collections.Counter(query)
                counts.update(query=query)
                return c
        """
        assert lint(src, UnboundedTelemetryTagRule(),
                    "m3_tpu/query/mod.py") == []

    def test_suppression_silences(self):
        src = """
            from m3_tpu.utils.instrument import ROOT

            def record(query):
                # DELIBERATE: test-only registry, cleared per run
                ROOT.sub_scope("t", query=query).counter("n").inc()  # m3lint: disable=unbounded-telemetry-tag
        """
        assert lint(src, UnboundedTelemetryTagRule(),
                    "m3_tpu/query/mod.py") == []


class TestUncheckedDiskIO:
    """unchecked-disk-io: broad handlers around direct file I/O in the
    persist plane without typed classification (persist/diskio.py's
    CorruptionError / DiskWriteError / classify_write_error taxonomy)."""

    # The seeded true positive: the pre-typed fileset-writer shape — an
    # ENOSPC swallowed whole, so nothing upstream ever trips the
    # read-only posture or withdraws the torn fileset.
    SEEDED = """
        import os

        def write_fileset(path, payload):
            try:
                with open(path, "wb") as f:
                    f.write(payload)
                os.replace(path, path[:-4])
            except Exception:
                return None
    """

    def test_seeded_positive_flags(self):
        found = lint(self.SEEDED, UncheckedDiskIORule(),
                     "m3_tpu/persist/fs.py")
        assert rule_ids(found) == ["unchecked-disk-io"]
        assert "classify_write_error" in found[0].message

    def test_bare_except_around_seam_io_flags(self):
        src = """
            def sync(io, f):
                try:
                    io.fsync(f)
                except:
                    pass
        """
        # `io.fsync` matches the seam-owner shape (_io/diskio/os/io).
        assert rule_ids(lint(src, UncheckedDiskIORule(),
                             "m3_tpu/persist/commitlog.py")) == \
            ["unchecked-disk-io"]

    def test_typed_handler_is_clean(self):
        src = """
            import os

            def remove(path):
                try:
                    os.remove(path)
                except OSError:
                    return False
                return True
        """
        assert lint(src, UncheckedDiskIORule(),
                    "m3_tpu/persist/fs.py") == []

    def test_classifying_handler_is_clean(self):
        src = """
            from .diskio import classify_write_error

            def write(path, payload):
                try:
                    with open(path, "wb") as f:
                        f.write(payload)
                except Exception as e:
                    raise classify_write_error(e, path) from e
        """
        assert lint(src, UncheckedDiskIORule(),
                    "m3_tpu/persist/fs.py") == []

    def test_bare_reraise_tail_is_clean(self):
        src = """
            import os

            def replace(src_p, dst_p, log):
                try:
                    os.replace(src_p, dst_p)
                except Exception:
                    log.warning("replace failed")
                    raise
        """
        assert lint(src, UncheckedDiskIORule(),
                    "m3_tpu/persist/fs.py") == []

    def test_typed_raise_in_handler_is_clean(self):
        src = """
            from .diskio import CorruptionError

            def read(path):
                try:
                    with open(path, "rb") as f:
                        return f.read()
                except Exception as e:
                    raise CorruptionError(str(e), path=path)
        """
        assert lint(src, UncheckedDiskIORule(),
                    "m3_tpu/persist/fs.py") == []

    def test_scoped_to_persist_and_seed_module_exempt(self):
        # Identical shape outside persist/ is another rule's business...
        assert lint(self.SEEDED, UncheckedDiskIORule(),
                    "m3_tpu/query/mod.py") == []
        # ...and diskio.py itself is where broad->typed translation lives.
        assert lint(self.SEEDED, UncheckedDiskIORule(),
                    "m3_tpu/persist/diskio.py") == []

    def test_non_io_try_is_clean(self):
        src = """
            def parse(blob):
                try:
                    return int(blob)
                except Exception:
                    return None
        """
        assert lint(src, UncheckedDiskIORule(),
                    "m3_tpu/persist/fs.py") == []

    def test_inner_typed_try_owns_its_io(self):
        src = """
            import os

            def robust(path):
                try:
                    try:
                        os.remove(path)
                    except OSError:
                        return False
                    return True
                except Exception:
                    return None
        """
        # The inner try's typed handler owns the I/O call; the outer
        # broad handler guards no direct I/O.
        assert lint(src, UncheckedDiskIORule(),
                    "m3_tpu/persist/fs.py") == []


class TestTreeGate:
    """THE gate: the real tree stays at zero non-suppressed findings.
    New rules (or new code) that introduce findings must fix them or add
    a justified `# m3lint: disable=<rule>` in the same change."""

    def test_tree_is_clean(self):
        findings, suppressed, nmods = run_paths([str(REPO / "m3_tpu")])
        assert nmods > 100  # sanity: the walk saw the whole package
        rendered = "\n".join(f.render() for f in findings)
        assert findings == [], f"m3lint findings on the tree:\n{rendered}"
        # the suppression mechanism is in real use (documented sites)
        assert suppressed >= 1


class TestFlushCallbackLoop:
    """per-datapoint-callback-in-flush: loops on the aggregator
    flush/emit paths invoking a per-datapoint `*_fn(...)` callback —
    the shape the columnar flush rebuild removed from Elem.emit
    (retained `*_ref` oracles are exempt by design)."""

    # The seeded true positive: the EXACT pre-columnar Elem.emit loop.
    PRE_CHANGE_ELEM_EMIT = """
        class Elem:
            def emit(self, window_start, stats_row, quantile_row,
                     flush_fn, forward_fn=None):
                end_nanos = window_start + self.resolution_ns
                for at in self.agg_types:
                    q = at.quantile()
                    value = quantile_row[q] if q is not None else \\
                        _stat_value(at, stats_row)
                    if self.key.pipeline.is_empty():
                        flush_fn(self._out_ids[at], end_nanos, value,
                                 self.key.storage_policy)
                    else:
                        self._process_pipeline(at, end_nanos, value,
                                               flush_fn, forward_fn)
    """

    def test_flags_the_pre_change_elem_emit_loop(self):
        found = lint(self.PRE_CHANGE_ELEM_EMIT, FlushCallbackLoopRule(),
                     "m3_tpu/aggregator/elem.py")
        assert rule_ids(found) == ["per-datapoint-callback-in-flush"]
        assert "flush_fn" in found[0].message

    def test_flags_forward_fn_loop_and_attribute_form(self):
        src = """
            def reduce_and_emit(jobs):
                for elem, start, vals, flush_fn, forward_fn in jobs:
                    forward_fn(elem.out_id, start, vals)

            class FlushManager:
                def flush(self, windows):
                    while windows:
                        w = windows.pop()
                        self._flush_fn(w.id, w.end, w.value, w.policy)
        """
        found = lint(src, FlushCallbackLoopRule(), "m3_tpu/aggregator/x.py")
        assert rule_ids(found) == ["per-datapoint-callback-in-flush"] * 2

    def test_ref_oracle_functions_exempt(self):
        src = """
            def reduce_and_emit_ref(jobs):
                for elem, start, vals, flush_fn, forward_fn in jobs:
                    flush_fn(elem.out_id, start, vals, elem.policy)
        """
        assert lint(src, FlushCallbackLoopRule(),
                    "m3_tpu/aggregator/list.py") == []

    def test_columnar_emit_and_map_shim_pass(self):
        # The post-rebuild shape: one columnar handler call per round,
        # per-datapoint compat driven by map (callback as ARGUMENT, not
        # a per-iteration call) — neither is the flagged loop shape.
        src = """
            def emit_batch(batch, flush_fn):
                for cls, rows in batch.classes.items():
                    ids = [e.out_id for e in rows.elems]
                    hb = getattr(flush_fn, "handle_columnar", None)
                    if hb is not None:
                        hb([(ids, rows.ends, rows.vals, cls.policy)])
                    else:
                        drain(map(flush_fn, ids, rows.ends, rows.vals))
        """
        assert lint(src, FlushCallbackLoopRule(),
                    "m3_tpu/aggregator/list.py") == []

    def test_non_flush_functions_and_other_dirs_not_scanned(self):
        src = """
            def route(items, send_fn):
                for it in items:
                    send_fn(it)
        """
        assert lint(src, FlushCallbackLoopRule(),
                    "m3_tpu/aggregator/client.py") == []
        flush_src = """
            def flush(items, flush_fn):
                for it in items:
                    flush_fn(it)
        """
        assert lint(flush_src, FlushCallbackLoopRule(),
                    "m3_tpu/storage/shard.py") == []

    def test_suppression(self):
        src = """
            def flush(items, flush_fn):
                # compat shim for plain-callable sinks
                # m3lint: disable=per-datapoint-callback-in-flush
                for it in items:
                    flush_fn(it)
        """
        assert lint(src, FlushCallbackLoopRule(),
                    "m3_tpu/aggregator/list.py") == []

    # The coordinator seeded true positive: the EXACT pre-change
    # Downsampler.write rollup loop — one add_untimed per rollup id per
    # ingested sample (metrics_appender.go SamplesAppender shape).
    PRE_CHANGE_DOWNSAMPLER_WRITE = """
        class Downsampler:
            def write(self, tags, t_nanos, value, metric_type):
                mid = _encode_tags(tags)
                result = self._matcher.match(mid)
                if result is None:
                    return False
                wrote = False
                for idm in result.for_new_rollup_ids:
                    mu = _to_union(metric_type, idm.id, value)
                    wrote = self._agg.add_untimed(mu, idm.metadatas) or wrote
                return wrote
    """

    def test_flags_the_pre_change_downsampler_write_loop(self):
        found = lint(self.PRE_CHANGE_DOWNSAMPLER_WRITE,
                     FlushCallbackLoopRule(),
                     "m3_tpu/coordinator/downsample.py")
        assert rule_ids(found) == ["per-datapoint-callback-in-flush"]
        assert "add_untimed" in found[0].message

    def test_downsampler_write_ref_oracle_exempt(self):
        src = """
            class Downsampler:
                def write_ref(self, tags, t_nanos, value, metric_type):
                    result = self._matcher.match(_encode_tags(tags))
                    for idm in result.for_new_rollup_ids:
                        self._agg.add_untimed(
                            _to_union(metric_type, idm.id, value),
                            idm.metadatas)
        """
        assert lint(src, FlushCallbackLoopRule(),
                    "m3_tpu/coordinator/downsample.py") == []

    def test_batched_downsampler_write_passes(self):
        # The post-change shape: grouped columnar adds — one
        # add_untimed_batch per (pipeline, policy) class, not one
        # add_untimed per datapoint. `add_untimed_batch` must NOT match
        # the exact-name `add_untimed` callback detector.
        src = """
            class Downsampler:
                def write_batch(self, samples):
                    groups = self._group(samples)
                    for _key, (metadatas, mus) in groups.items():
                        self._agg.add_untimed_batch(mus, metadatas)
        """
        assert lint(src, FlushCallbackLoopRule(),
                    "m3_tpu/coordinator/downsample.py") == []


class TestPerSeriesResultDict:
    """per-series-result-dict: per-row dict materialization inside
    result-path functions on the serving tree (coordinator/ query/
    rpc/); `_ref`-named oracles exempt (render_rules.py)."""

    PATH = "m3_tpu/coordinator/http_api.py"

    def test_flags_pre_change_matrix_renderer(self):
        # The EXACT pre-change coordinator renderer: one dict per
        # series, one [t, "v"] list per sample — the seeded positive
        # (bench r16 measured it at 1.07 responses/sec).
        src = '''
            import numpy as np

            def _prom_matrix(block):
                times = block.meta.times() / 1e9
                result = []
                for tags, row in zip(block.series_tags, block.values):
                    finite = np.isfinite(row)
                    if not finite.any():
                        continue
                    values = [[float(t), str(v)]
                              for t, v, ok in zip(times, row, finite) if ok]
                    result.append({"metric": dict(tags), "values": values})
                return {"status": "success",
                        "data": {"resultType": "matrix", "result": result}}
        '''
        from m3_tpu.analysis.render_rules import PerSeriesResultDictRule

        found = lint(src, PerSeriesResultDictRule(), self.PATH)
        assert rule_ids(found) == ["per-series-result-dict"]
        assert "_prom_matrix" in found[0].message

    def test_flags_dict_comprehension_and_yield(self):
        from m3_tpu.analysis.render_rules import PerSeriesResultDictRule

        src = """
            def render_series_result(block):
                return [{"metric": t, "values": list(r)}
                        for t, r in zip(block.series_tags, block.values)]
        """
        assert rule_ids(lint(src, PerSeriesResultDictRule(), self.PATH)) \
            == ["per-series-result-dict"]
        src = """
            def vector_rows(block):
                for t, r in zip(block.series_tags, block.values):
                    yield {"metric": t, "value": r[-1]}
        """
        assert rule_ids(lint(src, PerSeriesResultDictRule(), self.PATH)) \
            == ["per-series-result-dict"]

    def test_ref_oracles_exempt(self):
        from m3_tpu.analysis.render_rules import PerSeriesResultDictRule

        src = """
            def prom_matrix_ref(block):
                result = []
                for tags, row in zip(block.series_tags, block.values):
                    result.append({"metric": dict(tags),
                                   "values": list(row)})
                return result
        """
        assert lint(src, PerSeriesResultDictRule(), self.PATH) == []

    def test_columnar_renderer_and_nonresult_functions_pass(self):
        from m3_tpu.analysis.render_rules import PerSeriesResultDictRule

        # Columnar renderer: string chunks per series, no dicts.
        src = """
            def prom_matrix_bytes(block):
                chunks = []
                for r in range(len(block.series_tags)):
                    chunks.append("{...}")
                return ", ".join(chunks).encode()
        """
        assert lint(src, PerSeriesResultDictRule(), self.PATH) == []
        # Non-result-path function names are out of scope even with
        # per-row dicts (identity/tag metadata assembly is host work).
        src = """
            def rpc_fetch_tagged(ids):
                out = []
                for sid in ids:
                    out.append({"id": sid, "tags": {}})
                return out
        """
        assert lint(src, PerSeriesResultDictRule(), self.PATH) == []

    def test_out_of_scope_dirs_and_suppression(self):
        from m3_tpu.analysis.render_rules import PerSeriesResultDictRule

        src = """
            def render_result(rows):
                return [{"r": r} for r in rows]
        """
        # aggregator/ is not on the serving result plane.
        assert lint(src, PerSeriesResultDictRule(),
                    "m3_tpu/aggregator/flush.py") == []
        suppressed = """
            def render_result(rows):
                # m3lint: disable=per-series-result-dict
                return [{"r": r} for r in rows]
        """
        assert lint(suppressed, PerSeriesResultDictRule(), self.PATH) == []


class TestPerEntryReplay:
    """per-entry-replay: per-row registry/buffer loops on the recovery
    data plane (storage/bootstrap.py, persist/commitlog.py,
    persist/fs.py); `_ref`-named oracles exempt."""

    PATH = "m3_tpu/storage/bootstrap.py"

    def test_flags_pre_change_snapshot_install_loop(self):
        # the EXACT pre-change CommitlogBootstrapper shape: per-row
        # get_or_create + per-row write_batch(np.full(...)) — the
        # seeded positive this rule exists to keep out of the tree
        src = """
            import numpy as np

            def load_snapshots(shard, ids, ts, vals, npoints):
                for row, sid in enumerate(ids):
                    idx, _ = shard.registry.get_or_create(sid)
                    n = int(npoints[row])
                    shard.buffer.write_batch(
                        np.full(n, idx, np.int32),
                        np.asarray(ts[row, :n], np.int64),
                        np.asarray(vals[row, :n], np.float64),
                    )
        """
        found = lint(src, PerEntryReplayRule(), self.PATH)
        assert rule_ids(found) == ["per-entry-replay"] * 2
        assert "get_or_create" in found[0].message
        assert "np.full" in found[1].message

    def test_flags_per_row_remap_comprehension(self):
        # the pre-change FilesystemBootstrapper remap: one registry
        # probe per row inside a listcomp
        src = """
            import numpy as np

            def bootstrap(shard, blk, ids):
                remap = np.array(
                    [shard.registry.get_or_create(sid)[0] for sid in ids],
                    np.int32)
                shard.load_block(blk, remap)
        """
        found = lint(src, PerEntryReplayRule(), self.PATH)
        assert rule_ids(found) == ["per-entry-replay"]

    def test_ref_oracles_exempt(self):
        src = """
            import numpy as np

            def load_snapshots_ref(shard, ids, npoints, ts, vals):
                for row, sid in enumerate(ids):
                    idx, _ = shard.registry.get_or_create(sid)
                    shard.buffer.write_batch(
                        np.full(int(npoints[row]), idx, np.int32),
                        ts[row], vals[row])
        """
        assert lint(src, PerEntryReplayRule(), self.PATH) == []

    def test_batched_paths_pass(self):
        src = """
            import numpy as np

            def load_snapshots(shard, blk, ids, batches):
                remap, _created = shard.registry.get_or_create_batch(ids)
                shard.load_block(blk, np.asarray(remap, np.int32))
                for b in batches:
                    sidx, _ = shard.registry.get_or_create_batch(
                        b.ids.tolist())
                    shard.buffer.write_batch(
                        np.asarray(sidx, np.int32), b.t_ns, b.values)
        """
        assert lint(src, PerEntryReplayRule(), self.PATH) == []

    def test_out_of_scope_modules_pass(self):
        src = """
            def write(shard, sid):
                for s in [sid]:
                    shard.registry.get_or_create(s)
        """
        assert lint(src, PerEntryReplayRule(), "m3_tpu/storage/shard.py") == []
        assert lint(src, PerEntryReplayRule(), "m3_tpu/aggregator/map.py") == []

    def test_suppression(self):
        src = """
            def cold_path(shard, ids):
                # one-off admin repair tool, not the recovery plane
                # m3lint: disable=per-entry-replay
                for sid in ids:
                    shard.registry.get_or_create(sid)
        """
        assert lint(src, PerEntryReplayRule(), self.PATH) == []


# ===================================================================
# PR 12: whole-program analysis — callgraph, lifecycle dataflow,
# cross-module lock order, cross-module taint, seeded PR 4/6/8 shapes
# ===================================================================

from m3_tpu.analysis.callgraph import (CrossModuleLockOrderRule,  # noqa: E402
                                       ProgramIndex)
from m3_tpu.analysis.jax_rules import CrossModuleTaintRule  # noqa: E402
from m3_tpu.analysis.lifecycle_rules import (FinalizerUnderLockRule,  # noqa: E402
                                             LifecycleRule,
                                             ReleaseNoneParentLeakRule)


class TestCallGraphIndex:
    """ProgramIndex: import/alias resolution, receiver typing from
    __init__ assignments, return-type chaining, the global lock graph's
    Class.attr identities."""

    SRCS = {
        "m3_tpu/utils/widget.py": """
            import threading

            class Widget:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def poke(self) -> int:
                    with self._lock:
                        self.n += 1
                        return self.n


            def make_widget() -> Widget:
                return Widget()


            SHARED = Widget()
        """,
        "m3_tpu/storage/holder.py": """
            import threading
            from ..utils import widget
            from ..utils.widget import Widget as W, make_widget, SHARED

            class Holder:
                def __init__(self):
                    self._mu = threading.Lock()
                    self.direct = W()
                    self.via_mod = widget.Widget()
                    self.via_fn = make_widget()

                def run(self):
                    with self._mu:
                        self.direct.poke()

                def run_global(self):
                    with self._mu:
                        SHARED.poke()
        """,
    }

    def _index(self):
        return ProgramIndex.from_sources({
            rel: textwrap.dedent(src) for rel, src in self.SRCS.items()})

    def test_import_alias_and_symbol_resolution(self):
        idx = self._index()
        h = "m3_tpu.storage.holder"
        assert idx.resolve(h, "W") == ("class", "m3_tpu.utils.widget.Widget")
        assert idx.resolve(h, "widget.Widget") == (
            "class", "m3_tpu.utils.widget.Widget")
        assert idx.resolve(h, "make_widget") == (
            "func", "m3_tpu.utils.widget.make_widget")
        assert idx.resolve(h, "widget")[0] == "module"

    def test_receiver_typing_from_init_assignments(self):
        idx = self._index()
        holder = idx.classes["m3_tpu.storage.holder.Holder"]
        w = "m3_tpu.utils.widget.Widget"
        # ctor by alias, ctor through a module alias, and a typed
        # factory return all land on the same class
        assert holder.attr_types["direct"] == w
        assert holder.attr_types["via_mod"] == w
        assert holder.attr_types["via_fn"] == w

    def test_module_global_singleton_typing(self):
        idx = self._index()
        assert idx.global_types["m3_tpu.utils.widget.SHARED"] == \
            "m3_tpu.utils.widget.Widget"

    def test_cross_module_lock_edges_use_class_attr_identity(self):
        idx = self._index()
        edges = idx.lock_edges()
        # Holder.run holds Holder._mu and calls Widget.poke, which
        # acquires Widget._lock — in ANOTHER module
        assert ("Holder._mu", "Widget._lock") in edges
        # the module-global singleton path resolves identically
        path, _line, via = edges[("Holder._mu", "Widget._lock")]
        assert path == "m3_tpu/storage/holder.py"
        assert via.endswith("Widget.poke")

    def test_lock_kinds(self):
        idx = self._index()
        kinds = idx.lock_kinds()
        assert kinds["Widget._lock"] == "lock"
        assert kinds["Holder._mu"] == "lock"

    def test_condition_over_lock_aliases_to_wrapped_identity(self):
        # self._cv = Condition(self._mu): acquisitions through the
        # condition ARE acquisitions of _mu — the runtime witness sees
        # _mu's proxy, so the static identity must match
        srcs = {
            "m3_tpu/storage/cv.py": """
                import threading

                class Waiter:
                    def __init__(self):
                        self._outer = threading.Lock()
                        self._mu = threading.Lock()
                        self._cv = threading.Condition(self._mu)

                    def run(self):
                        with self._outer:
                            with self._cv:
                                pass
            """,
        }
        idx = ProgramIndex.from_sources(
            {rel: textwrap.dedent(s) for rel, s in srcs.items()})
        edges = idx.lock_edges()
        assert ("Waiter._outer", "Waiter._mu") in edges
        assert not any(b == "Waiter._cv" for _a, b in edges)

    def test_sibling_with_items_record_an_edge(self):
        # `with a, b:` acquires sequentially — the witness records a->b,
        # so the static graph must too (ABBA written this way included)
        srcs = {
            "m3_tpu/storage/sib.py": """
                import threading

                class Pair:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def both(self):
                        with self._a, self._b:
                            pass
            """,
        }
        idx = ProgramIndex.from_sources(
            {rel: textwrap.dedent(s) for rel, s in srcs.items()})
        assert ("Pair._a", "Pair._b") in idx.lock_edges()


class TestCrossModuleLockOrder:
    """The PR 6 contract shape: tenant-lock -> budget-lock in storage/,
    budget-lock -> tenant-lock in utils/ — invisible per-module,
    detected on the program-wide graph."""

    SRCS = {
        "m3_tpu/utils/budget.py": """
            import threading
            from ..storage.tile_cache import TileCache

            class Budget:
                def __init__(self, tenant: TileCache):
                    self._lock = threading.Lock()
                    self.tenant = tenant

                def reclaim(self):
                    with self._lock:
                        self.tenant.evict_one()
        """,
        "m3_tpu/storage/tile_cache.py": """
            import threading

            class TileCache:
                def __init__(self, budget):
                    self._lock = threading.Lock()
                    self.budget = budget

                def put(self, k, v):
                    with self._lock:
                        self.budget.reclaim()

                def evict_one(self):
                    with self._lock:
                        return 1
        """,
    }

    def _index(self, extra=None):
        srcs = {rel: textwrap.dedent(s)
                for rel, s in {**self.SRCS, **(extra or {})}.items()}
        return ProgramIndex.from_sources(srcs)

    def test_cross_module_abba_detected(self):
        idx = self._index()
        # wire the one dynamic hop (budget param is untyped on the
        # storage side) the way the real PR 6 code types it
        idx.classes["m3_tpu.storage.tile_cache.TileCache"].attr_types[
            "budget"] = "m3_tpu.utils.budget.Budget"
        found = list(CrossModuleLockOrderRule().check_program(idx))
        inv = [f for f in found if "inversion" in f.message]
        assert inv, [f.render() for f in found]
        msg = inv[0].message
        assert "TileCache._lock" in msg and "Budget._lock" in msg
        # both files are named so the reviewer sees the full loop
        assert "utils/budget.py" in msg or "tile_cache" in inv[0].path

    def test_one_consistent_order_is_clean(self):
        # budget never calls back into the tenant -> one global order
        extra = {
            "m3_tpu/utils/budget.py": """
                import threading

                class Budget:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def reclaim(self):
                        with self._lock:
                            return 0
            """,
        }
        idx = self._index(extra)
        idx.classes["m3_tpu.storage.tile_cache.TileCache"].attr_types[
            "budget"] = "m3_tpu.utils.budget.Budget"
        assert list(CrossModuleLockOrderRule().check_program(idx)) == []


class TestCrossModuleTaint:
    """A jitted kernel calling an imported helper with a traced value:
    the callee's Python branch is a trace error the per-module pass
    cannot see."""

    SRCS = {
        "m3_tpu/ops/kernel.py": """
            import jax
            import jax.numpy as jnp
            from .helpers import clamp

            @jax.jit
            def step(x):
                return clamp(x) + 1
        """,
        "m3_tpu/ops/helpers.py": """
            def clamp(v):
                if v > 0:
                    return v
                return 0
        """,
    }

    def _run(self, srcs):
        idx = ProgramIndex.from_sources(
            {rel: textwrap.dedent(s) for rel, s in srcs.items()})
        return list(CrossModuleTaintRule().check_program(idx))

    def test_tainted_branch_in_imported_helper_flags(self):
        found = self._run(self.SRCS)
        assert [f.rule for f in found] == ["jax-traced-branch"]
        assert found[0].path == "m3_tpu/ops/helpers.py"
        assert "cross-module call from m3_tpu/ops/kernel.py" in \
            found[0].message

    def test_untainted_cross_module_call_is_clean(self):
        srcs = dict(self.SRCS)
        srcs["m3_tpu/ops/kernel.py"] = """
            import jax
            from .helpers import clamp

            @jax.jit
            def step(x, n: int):
                _ = clamp(7)
                return x + 1
        """
        assert self._run(srcs) == []

    def test_callee_jitted_at_home_left_to_per_module_pass(self):
        srcs = dict(self.SRCS)
        srcs["m3_tpu/ops/helpers.py"] = """
            import jax

            @jax.jit
            def clamp(v):
                if v > 0:
                    return v
                return 0
        """
        # the per-module JaxPurityRule owns this finding; the program
        # rule must not double-report it
        assert self._run(srcs) == []

    def test_taint_transitive_helper_reaches_imported_module(self):
        # jitted f -> local helper g -> imported h(tracer): the external
        # call leaves the module one hop BELOW the traced function
        srcs = {
            "m3_tpu/ops/kernel.py": """
                import jax
                from .helpers import clamp

                def _local(y):
                    return clamp(y)

                @jax.jit
                def step(x):
                    return _local(x) + 1
            """,
            "m3_tpu/ops/helpers.py": """
                def clamp(v):
                    if v > 0:
                        return v
                    return 0
            """,
        }
        found = self._run(srcs)
        assert [f.rule for f in found] == ["jax-traced-branch"]
        assert found[0].path == "m3_tpu/ops/helpers.py"

    def test_taint_continues_into_callee_local_helpers(self):
        # jitted f -> imported h(tracer) -> h's SAME-module helper g:
        # the tracer keeps flowing after the cross-module hop
        srcs = {
            "m3_tpu/ops/kernel.py": """
                import jax
                from .helpers import outer

                @jax.jit
                def step(x):
                    return outer(x) + 1
            """,
            "m3_tpu/ops/helpers.py": """
                def _inner(w):
                    if w > 0:
                        return w
                    return 0

                def outer(v):
                    return _inner(v)
            """,
        }
        found = self._run(srcs)
        assert [f.rule for f in found] == ["jax-traced-branch"]
        assert found[0].path == "m3_tpu/ops/helpers.py"
        assert "_inner" in found[0].message or found[0].line


class TestLifecycleRule:
    """Path-sensitive paired-op balance: gate admit/release, breaker
    allow/settle, spans — every path including the exceptional ones."""

    REL = "m3_tpu/coordinator/mod.py"

    def test_admit_without_exception_protection_flags(self):
        src = """
            def ingest(self, payload):
                metrics = decode(payload)
                self.gate.admit(len(metrics))
                for m in metrics:
                    self.storage.write(m)
                self.gate.release(len(metrics))
        """
        found = lint(src, LifecycleRule(), self.REL)
        assert rule_ids(found) == ["lifecycle-exception-leak"]
        assert "gate-admit" in found[0].message

    def test_try_finally_release_is_balanced(self):
        src = """
            def ingest(self, payload):
                metrics = decode(payload)
                self.gate.admit(len(metrics))
                try:
                    for m in metrics:
                        self.storage.write(m)
                finally:
                    self.gate.release(len(metrics))
        """
        assert lint(src, LifecycleRule(), self.REL) == []

    def test_guard_conditioned_admit_release_mirror_is_balanced(self):
        # the coordinator M3MsgIngester shape: admit under a None-guard,
        # release mirror-guarded in the finally
        src = """
            def consume(self, payload):
                metrics = decode(payload)
                gate = self.gate
                if gate is not None:
                    gate.admit(len(metrics))
                try:
                    for m in metrics:
                        self.storage.write(m)
                finally:
                    if gate is not None:
                        gate.release(len(metrics))
        """
        assert lint(src, LifecycleRule(), self.REL) == []

    def test_held_context_form_is_balanced(self):
        src = """
            def handle(self, n):
                with self.gate.held(n):
                    self.storage.write(n)
        """
        assert lint(src, LifecycleRule(), self.REL) == []

    def test_breaker_allow_early_return_leaks(self):
        src = """
            def call_once(self):
                if not self.breaker.allow():
                    raise BreakerOpen("shed")
                resp = self.do_io()
                self.breaker.record_success()
                return resp
        """
        found = lint(src, LifecycleRule(), "m3_tpu/client/mod.py")
        assert rule_ids(found) == ["lifecycle-exception-leak"]
        assert "breaker-allow" in found[0].message

    def test_guard_with_explicit_else_branch_is_balanced(self):
        # the grant lives in the ELSE of the negated guard
        src = """
            def call_once(self):
                if not self.breaker.allow():
                    raise BreakerOpen("shed")
                else:
                    try:
                        resp = self.do_io()
                    except BaseException:
                        self.breaker.record_failure()
                        raise
                    self.breaker.record_success()
                    return resp
        """
        assert lint(src, LifecycleRule(), "m3_tpu/client/mod.py") == []

    def test_canonical_settle_every_exit_is_balanced(self):
        src = """
            def call_once(self):
                if not self.breaker.allow():
                    raise BreakerOpen("shed")
                try:
                    resp = self.do_io()
                except BaseException:
                    self.breaker.record_failure()
                    raise
                self.breaker.record_success()
                return resp
        """
        assert lint(src, LifecycleRule(), "m3_tpu/client/mod.py") == []

    def test_settle_through_local_closure_and_callee_handoff(self):
        # the client/session.py shape: a local `record` closure settles
        # through self._record, and the grant is handed to the callee
        src = """
            def call_once(self):
                if not self.breaker.allow():
                    raise BreakerOpen("shed")
                recorded = [False]

                def record(ok):
                    if not recorded[0]:
                        recorded[0] = True
                        self._record(ok)

                try:
                    return self._on_conn(record)
                except BaseException:
                    record(False)
                    raise

            def _record(self, ok):
                if ok:
                    self.breaker.record_success()
                else:
                    self.breaker.record_failure()
        """
        assert lint(src, LifecycleRule(), "m3_tpu/client/mod.py") == []

    def test_cross_method_protocol_is_exempt(self):
        # the insert-queue shape: admit on insert, release on drain
        src = """
            class Queue:
                def insert(self, group):
                    self.gate.admit(len(group))
                    self._pending.append(group)

                def _drain(self):
                    n = self._apply()
                    self.gate.release(n)
        """
        assert lint(src, LifecycleRule(), "m3_tpu/storage/mod.py") == []

    def test_scope_owned_receiver_is_exempt(self):
        # the query-executor shape: the charge bills a thread-locally
        # installed enforcer whose OWNER releases in its finally
        src = """
            def _fetch(self, sel):
                series = self.storage.fetch_raw(sel)
                enforcer = getattr(self._local, "enforcer", None)
                if enforcer is not None:
                    enforcer.add(len(series))
                return series
        """
        assert lint(src, LifecycleRule(), "m3_tpu/query/mod.py") == []

    def test_return_of_handle_is_a_legal_transfer(self):
        src = """
            def open_scope(self, n):
                self.gate.admit(n)
                return self.gate
        """
        assert lint(src, LifecycleRule(), self.REL) == []


class TestSpanUnfinished:
    """The PR 8 straggler-replica shape: a manually-entered span left
    open on the early-quorum return path."""

    def test_straggler_early_return_flags(self):
        src = """
            from m3_tpu.utils import tracing

            def fanout(self, hosts):
                sp = tracing.TRACER.span("replica.fanout")
                sp.__enter__()
                for h in hosts:
                    self.submit(h)
                    if self.quorum_met():
                        return
                sp.__exit__(None, None, None)
        """
        found = lint(src, LifecycleRule(), "m3_tpu/client/mod.py")
        assert rule_ids(found) == ["span-unfinished"]
        assert "straggler" in found[0].message

    def test_with_form_is_balanced(self):
        src = """
            from m3_tpu.utils import tracing

            def fanout(self, hosts):
                with tracing.TRACER.span("replica.fanout") as sp:
                    for h in hosts:
                        self.submit(h)
                        if self.quorum_met():
                            return
        """
        assert lint(src, LifecycleRule(), "m3_tpu/client/mod.py") == []

    def test_enter_with_try_finally_exit_is_balanced(self):
        src = """
            from m3_tpu.utils import tracing

            def fanout(self, hosts):
                sp = tracing.TRACER.span("replica.fanout")
                sp.__enter__()
                try:
                    for h in hosts:
                        self.submit(h)
                        if self.quorum_met():
                            return
                finally:
                    sp.__exit__(None, None, None)
        """
        assert lint(src, LifecycleRule(), "m3_tpu/client/mod.py") == []


class TestReleaseNoneParentLeak:
    """The historical PR 4 Enforcer.release(None) leak, reintroduced."""

    PRE_FIX = """
        class Enforcer:
            def __init__(self, limit=None, parent=None):
                self.parent = parent
                self._current = 0.0

            def release(self, cost=None):
                with self._lock:
                    if cost is None:
                        self._current = 0.0
                    else:
                        self._current -= cost
                if self.parent is not None and cost:
                    self.parent.release(cost)
    """

    def test_flags_the_pre_fix_enforcer_shape(self):
        found = lint(self.PRE_FIX, ReleaseNoneParentLeakRule(),
                     "m3_tpu/utils/mycost.py")
        assert rule_ids(found) == ["release-none-parent-leak"]
        assert "truthiness" in found[0].message or \
            "maybe-None" in found[0].message

    def test_flags_forwarding_the_raw_param(self):
        src = """
            class Enforcer:
                def __init__(self, parent=None):
                    self.parent = parent

                def release(self, cost=None):
                    self._current -= cost or self._current
                    if self.parent is not None:
                        self.parent.release(cost)
        """
        found = lint(src, ReleaseNoneParentLeakRule(), "m3_tpu/utils/c.py")
        assert rule_ids(found) == ["release-none-parent-leak"]

    def test_fixed_captured_amount_shape_is_clean(self):
        src = """
            class Enforcer:
                def __init__(self, parent=None):
                    self.parent = parent
                    self._current = 0.0

                def release(self, cost=None):
                    with self._lock:
                        released = self._current if cost is None else cost
                        self._current -= released
                    if self.parent is not None and released:
                        self.parent.release(released)
        """
        assert lint(src, ReleaseNoneParentLeakRule(),
                    "m3_tpu/utils/c.py") == []


class TestFinalizerUnderLock:
    """The PR 6 HBMBudget shape: a weakref.finalize callback acquiring
    the budget lock — a latent self-deadlock at any bytecode boundary."""

    def test_flags_locking_finalizer(self):
        src = """
            import threading
            import weakref

            class Budget:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._transient = 0

                def _release_transient(self, n):
                    with self._lock:
                        self._transient -= n

                def device_put(self, dev, n):
                    weakref.finalize(dev, self._release_transient, n)
        """
        found = lint(src, FinalizerUnderLockRule(), "m3_tpu/utils/b.py")
        assert rule_ids(found) == ["finalizer-under-lock"]
        assert "_release_transient" in found[0].message

    def test_flags_one_call_level_deep(self):
        src = """
            import threading
            import weakref

            class Budget:
                def __init__(self):
                    self._lock = threading.Lock()

                def _locked_sub(self, n):
                    with self._lock:
                        return n

                def _release(self, n):
                    self._locked_sub(n)

                def device_put(self, dev, n):
                    weakref.finalize(dev, self._release, n)
        """
        found = lint(src, FinalizerUnderLockRule(), "m3_tpu/utils/b.py")
        assert rule_ids(found) == ["finalizer-under-lock"]

    def test_lock_free_append_drain_pattern_is_clean(self):
        src = """
            import threading
            import weakref

            class Budget:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._released = []

                def _release_transient(self, n):
                    self._released.append(n)

                def usage(self):
                    with self._lock:
                        while self._released:
                            self._transient -= self._released.pop()

                def device_put(self, dev, n):
                    weakref.finalize(dev, self._release_transient, n)
        """
        assert lint(src, FinalizerUnderLockRule(), "m3_tpu/utils/b.py") == []


class TestNewFamiliesTreeGate:
    """Zero-findings gate for ONLY the PR 12 families — isolates a
    regression in these rules from the umbrella TestTreeGate."""

    def test_tree_clean_under_lifecycle_families(self):
        rules = [LifecycleRule(), ReleaseNoneParentLeakRule(),
                 FinalizerUnderLockRule()]
        findings, _sup, nmods = run_paths(
            [str(REPO / "m3_tpu")], rules, program_rules=[])
        assert nmods > 100
        rendered = "\n".join(f.render() for f in findings)
        assert findings == [], f"lifecycle findings on the tree:\n{rendered}"

    def test_tree_clean_under_program_rules(self):
        from m3_tpu.analysis.core import iter_modules, run_program

        mods = list(iter_modules([str(REPO / "m3_tpu")]))
        findings, _sup = run_program(mods)
        rendered = "\n".join(f.render() for f in findings)
        assert findings == [], f"program findings on the tree:\n{rendered}"


class TestNumericDtypeRule:
    """numeric_rules dtype dataflow: f64-downcast-on-exact-path /
    f64-reduce-of-f32 / abs-f32-comparison — the exact-contract plane
    (ops/, parallel/, query/plan.py)."""

    def test_flags_silent_downcast_of_f64_plane(self):
        # The historical exact-contract downcast shape: a counter grid
        # staged f32 with no residual split — the f64 host-reduce
        # exactness silently gone.
        src = """
            import numpy as np

            def stage(raw):
                grid = np.asarray(raw, dtype=np.float64)
                return grid.astype(np.float32)
        """
        found = lint(src, DtypeDataflowRule(), "m3_tpu/parallel/stage.py")
        assert rule_ids(found) == ["f64-downcast-on-exact-path"]

    def test_residual_split_is_fine(self):
        # temporal.center's own shape: the downcast operand IS the
        # residual (a difference), which is downcast-safe by contract.
        src = """
            import numpy as np

            def center(values):
                values = np.asarray(values, dtype=np.float64)
                finite = np.isfinite(values)
                baseline = np.where(finite.any(axis=1), values[:, 0], 0.0)
                resid = (values - baseline[:, None]).astype(np.float32)
                return resid, baseline
        """
        assert lint(src, DtypeDataflowRule(), "m3_tpu/ops/t.py") == []

    def test_double_f32_split_is_fine(self):
        # The `value2` exact split (PR 16 topk ranking): hi is a lossy
        # downcast but gp also feeds the lo-residual subtraction.
        src = """
            import numpy as np

            def split(raw):
                gp = np.asarray(raw, dtype=np.float64)
                hi = gp.astype(np.float32)
                lo = (gp - hi.astype(np.float64)).astype(np.float32)
                return hi, lo
        """
        assert lint(src, DtypeDataflowRule(), "m3_tpu/parallel/s.py") == []

    def test_live_f64_companion_is_fine(self):
        # temporal._resid_args: base32 rides BESIDE the f64 base (the
        # host finish reads the exact plane) — not a silent downcast.
        src = """
            import numpy as np

            def center(values):
                return values, values[:, 0]

            def resid_args(g):
                g = np.asarray(g, dtype=np.float64)
                resid, base = center(g)
                base32 = base.astype(np.float32)
                return resid, base, base32
        """
        found = [f for f in lint(src, DtypeDataflowRule(), "m3_tpu/ops/t.py")
                 if f.rule == "f64-downcast-on-exact-path"]
        assert found == []

    def test_center_baseline_signature_downcast_flags(self):
        # The dropped-baseline shape: center()'s f64 baseline downcast
        # with neither a residual companion nor the f64 plane kept.
        src = """
            import numpy as np
            from m3_tpu.ops.temporal import center

            def stage(gp):
                resid, base = center(gp)
                return [resid, base.astype(np.float32)]
        """
        found = lint(src, DtypeDataflowRule(), "m3_tpu/parallel/c.py")
        assert rule_ids(found) == ["f64-downcast-on-exact-path"]

    def test_flags_f64_reduce_of_f32(self):
        # Upcast-after-accumulation-input: the f64 dtype on the reduce
        # recovers nothing the f32 plane already lost.
        src = """
            import numpy as np

            def total(raw):
                v32 = np.zeros((4, 4), dtype=np.float32)
                v32[:] = raw
                return v32.astype(np.float64).sum(axis=0)
        """
        found = lint(src, DtypeDataflowRule(), "m3_tpu/ops/r.py")
        assert rule_ids(found) == ["f64-reduce-of-f32"]

    def test_flags_dtype_kwarg_reduce_of_f32(self):
        src = """
            import numpy as np

            def total(raw):
                v32 = np.asarray(raw, dtype=np.float32)
                return np.sum(v32, dtype=np.float64)
        """
        found = lint(src, DtypeDataflowRule(), "m3_tpu/ops/r.py")
        assert rule_ids(found) == ["f64-reduce-of-f32"]

    def test_residual_provenance_reduce_is_fine(self):
        # Residual-space f32 feeding an f64 reduce is exactly the
        # sanctioned decomposition (device residual sum + host baseline).
        src = """
            import numpy as np

            def total(values, baseline):
                resid = (values - baseline[:, None]).astype(np.float32)
                return np.sum(resid, dtype=np.float64)
        """
        assert lint(src, DtypeDataflowRule(), "m3_tpu/ops/r.py") == []

    def test_flags_comparison_on_lossy_f32_plane(self):
        # The abs-comparison bug class the interpreter-fallback policy
        # dodges: thresholding a downcast counter plane.
        src = """
            import numpy as np

            def filt(raw, threshold):
                grid = np.asarray(raw, dtype=np.float64)
                v = grid.astype(np.float32)
                w = v * 1.0
                return w > threshold
        """
        found = lint(src, DtypeDataflowRule(), "m3_tpu/query/plan.py")
        assert "abs-f32-comparison" in rule_ids(found)

    def test_comparison_on_f64_or_residual_plane_is_fine(self):
        src = """
            import numpy as np

            def filt(raw, threshold):
                grid = np.asarray(raw, dtype=np.float64)
                resid = (grid - grid[:, :1]).astype(np.float32)
                return (grid > threshold) | (resid > 0.5)
        """
        found = [f for f in lint(src, DtypeDataflowRule(),
                                 "m3_tpu/query/plan.py")
                 if f.rule == "abs-f32-comparison"]
        assert found == []

    def test_ref_oracles_exempt(self):
        src = """
            import numpy as np

            def stage_ref(raw):
                grid = np.asarray(raw, dtype=np.float64)
                return grid.astype(np.float32)
        """
        assert lint(src, DtypeDataflowRule(), "m3_tpu/ops/t.py") == []

    def test_out_of_scope_dirs_skipped(self):
        src = """
            import numpy as np

            def stage(raw):
                grid = np.asarray(raw, dtype=np.float64)
                return grid.astype(np.float32)
        """
        assert lint(src, DtypeDataflowRule(), "m3_tpu/storage/db.py") == []
        # query/ outside plan.py is host label algebra, out of scope
        assert lint(src, DtypeDataflowRule(), "m3_tpu/query/render.py") == []

    def test_suppression_silences(self):
        src = """
            import numpy as np

            def stage(raw):
                grid = np.asarray(raw, dtype=np.float64)
                # exactness recovered on host  # m3lint: disable=f64-downcast-on-exact-path
                return grid.astype(np.float32)
        """
        assert lint(src, DtypeDataflowRule(), "m3_tpu/ops/t.py") == []


class TestSentinelTaintRule:
    """numeric_rules sentinel taint: pad-lane-aggregate /
    unmasked-sentinel-gather — NaN row padding and -1 index sentinels
    must meet a mask/where/clamp before aggregates and gathers."""

    def test_flags_padding_lanes_into_psum_aggregate(self):
        # Historical shape 1: NaN-padded rows folding straight into a
        # segment reduce + psum fan-in (no where-mask).
        src = """
            import jax
            import jax.numpy as jnp
            import numpy as np

            def fan_in(grid, gids, g_pad):
                padded = np.full((8, 16), np.nan)
                padded[:4, :12] = grid
                s = jax.ops.segment_sum(padded, gids, num_segments=g_pad)
                return jax.lax.psum(s, "shard")
        """
        found = lint(src, SentinelTaintRule(), "m3_tpu/parallel/c.py")
        assert rule_ids(found) == ["pad-lane-aggregate"]

    def test_where_mask_before_reduce_is_fine(self):
        # The PR 9 contract negative: every segment reduce behind
        # jnp.where(mask, v, 0.0).
        src = """
            import jax
            import jax.numpy as jnp
            import numpy as np

            def fan_in(grid, gids, g_pad):
                padded = np.full((8, 16), np.nan)
                padded[:4, :12] = grid
                mask = jnp.isfinite(padded)
                z = jnp.where(mask, padded, 0.0)
                s = jax.ops.segment_sum(z, gids, num_segments=g_pad)
                return jax.lax.psum(s, "shard")
        """
        assert lint(src, SentinelTaintRule(), "m3_tpu/parallel/c.py") == []

    def test_flags_unmasked_vv_gather(self):
        # Historical shape 2: the vv index map gathered raw — the -1
        # sentinel wraps to the LAST row and replays its live values.
        src = """
            import numpy as np

            def vv(many_v, pairs, r_pad):
                many_idx = np.full(r_pad, -1, dtype=np.int32)
                many_idx[:len(pairs)] = pairs
                return many_v[many_idx]
        """
        found = lint(src, SentinelTaintRule(), "m3_tpu/parallel/c.py")
        assert rule_ids(found) == ["unmasked-sentinel-gather"]

    def test_clamped_gather_is_fine(self):
        # The PR 16 `_sub_gather`/vv contract negative: clamp + valid
        # mask.
        src = """
            import jax.numpy as jnp
            import numpy as np

            def vv(many_v, pairs, r_pad):
                many_idx = np.full(r_pad, -1, dtype=np.int32)
                many_idx[:len(pairs)] = pairs
                valid = (many_idx >= 0)[:, None]
                a = many_v[jnp.maximum(many_idx, 0)]
                return jnp.where(valid, a, jnp.nan)
        """
        assert lint(src, SentinelTaintRule(), "m3_tpu/parallel/c.py") == []

    def test_flags_where_built_sentinel_into_take(self):
        # plan.py's packed-column construction (np.where(valid, c, -1))
        # IS the sentinel source; consuming it untreated flags.
        src = """
            import jax.numpy as jnp
            import numpy as np

            def packed(arr, cols, valid):
                cmap = np.where(valid, cols, -1)
                return jnp.take(arr, cmap, axis=1)
        """
        found = lint(src, SentinelTaintRule(), "m3_tpu/query/plan.py")
        assert rule_ids(found) == ["unmasked-sentinel-gather"]

    def test_flags_neg1_ids_into_segment_and_add_at(self):
        src = """
            import jax
            import numpy as np

            def agg(v, n, g):
                gids = np.full(n, -1, dtype=np.int64)
                out = np.zeros((g, v.shape[1]))
                np.add.at(out, gids, v)
                return jax.ops.segment_sum(v, gids, num_segments=g)
        """
        found = rule_ids(lint(src, SentinelTaintRule(), "m3_tpu/ops/a.py"))
        assert found == ["unmasked-sentinel-gather"] * 2

    def test_pad_neutral_ops_pass(self):
        src = """
            import jax.numpy as jnp
            import numpy as np

            def reduce(grid):
                padded = np.full((8, 16), np.nan)
                padded[:4] = grid
                return jnp.nansum(padded, axis=0), np.nanmax(padded)
        """
        assert lint(src, SentinelTaintRule(), "m3_tpu/ops/t.py") == []

    def test_pad_grid_source_flags_and_masked_passes(self):
        src = """
            import jax.numpy as jnp

            def _pad_grid(g, s, t):
                return g

            def bad(g):
                gp = _pad_grid(g, 8, 16)
                return jnp.sum(gp, axis=0)

            def good(g):
                gp = _pad_grid(g, 8, 16)
                return jnp.sum(jnp.where(jnp.isfinite(gp), gp, 0.0), axis=0)
        """
        found = lint(src, SentinelTaintRule(), "m3_tpu/parallel/c.py")
        assert rule_ids(found) == ["pad-lane-aggregate"]

    def test_method_sum_on_padded_receiver_flags(self):
        src = """
            import numpy as np

            def total(grid):
                padded = np.full((8, 16), np.nan)
                padded[:4] = grid
                return padded.sum(axis=0)
        """
        found = lint(src, SentinelTaintRule(), "m3_tpu/ops/t.py")
        assert rule_ids(found) == ["pad-lane-aggregate"]

    def test_ref_oracles_and_out_of_scope_skipped(self):
        src = """
            import numpy as np

            def total_ref(grid):
                padded = np.full((8, 16), np.nan)
                padded[:4] = grid
                return padded.sum(axis=0)
        """
        assert lint(src, SentinelTaintRule(), "m3_tpu/ops/t.py") == []
        bad = src.replace("total_ref", "total")
        assert lint(bad, SentinelTaintRule(), "m3_tpu/storage/db.py") == []

    def test_suppression_with_justification(self):
        src = """
            import numpy as np

            def total(grid):
                padded = np.full((8, 16), np.nan)
                padded[:4] = grid
                # pad-neutral by construction (all-finite input)
                # m3lint: disable=pad-lane-aggregate
                return padded.sum(axis=0)
        """
        assert lint(src, SentinelTaintRule(), "m3_tpu/ops/t.py") == []


class TestMeshSpecRule:
    """jax_rules mesh-spec checker: mesh-axis-unbound /
    shard-spec-arity / unannotated-out-sharding."""

    def test_flags_psum_axis_absent_from_mesh(self):
        # Historical shape 3: a collective over an axis name the bound
        # mesh does not carry (typo'd "shards" vs "shard").
        src = """
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            def make(devs):
                return Mesh(np.asarray(devs), ("shard", "time"))

            def fan_in(part):
                return jax.lax.psum(part, "shards")
        """
        found = lint(src, MeshSpecRule(), "m3_tpu/parallel/q.py")
        assert rule_ids(found) == ["mesh-axis-unbound"]
        assert "'shards'" in found[0].message

    def test_bound_axes_and_spec_vocabulary_pass(self):
        # The ingest/query shapes: axes declared by the Mesh ctor and by
        # P(...) literals (nested-tuple grouping included) all count.
        src = """
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            def make(devs):
                return Mesh(np.asarray(devs).reshape(2, 2), ("shard", "time"))

            def fan_in(part, blk):
                rowc = P(("shard", "time"), None)
                s = jax.lax.psum(part, "shard")
                return jax.lax.pmin(blk, "time"), s, rowc
        """
        assert lint(src, MeshSpecRule(), "m3_tpu/parallel/i.py") == []

    def test_module_without_declared_axes_is_skipped(self):
        src = """
            import jax

            def fan_in(part):
                return jax.lax.psum(part, "shard")
        """
        assert lint(src, MeshSpecRule(), "m3_tpu/parallel/h.py") == []

    def test_flags_in_specs_arity_mismatch(self):
        src = """
            import jax
            from jax.sharding import PartitionSpec as P

            def shard_map_compat(fn, *, mesh, in_specs, out_specs):
                return fn

            def build(mesh):
                def local(values, counts):
                    return values

                return shard_map_compat(local, mesh=mesh,
                                        in_specs=(P("shard"),),
                                        out_specs=P("shard"))
        """
        found = lint(src, MeshSpecRule(), "m3_tpu/parallel/a.py")
        assert rule_ids(found) == ["shard-spec-arity"]

    def test_matching_arity_and_name_bound_specs_pass(self):
        src = """
            import jax
            from jax.sharding import PartitionSpec as P

            def shard_map_compat(fn, *, mesh, in_specs, out_specs):
                return fn

            def build(mesh):
                def local(values, counts):
                    return values

                specs = (P("shard"), P("shard"))
                return shard_map_compat(local, mesh=mesh, in_specs=specs,
                                        out_specs=P("shard"))
        """
        assert lint(src, MeshSpecRule(), "m3_tpu/parallel/a.py") == []

    def test_flags_unconditional_sharded_out_spec_in_compile(self):
        src = """
            import jax
            from jax.sharding import PartitionSpec as P

            def shard_map_compat(fn, *, mesh, in_specs, out_specs):
                return fn

            def plan_executable(body, mesh):
                return shard_map_compat(body, mesh=mesh,
                                        in_specs=(P("shard", None),),
                                        out_specs=(P("shard", None),))
        """
        found = lint(src, MeshSpecRule(), "m3_tpu/parallel/compile.py")
        assert "unannotated-out-sharding" in rule_ids(found)

    def test_edge_annotated_out_spec_passes(self):
        # The real compile.py shape: the sharded out spec bound by an
        # IfExp on the root edge's SHARDED annotation.
        src = """
            import jax
            from jax.sharding import PartitionSpec as P

            SHARDED = "shard"

            def shard_map_compat(fn, *, mesh, in_specs, out_specs):
                return fn

            def plan_executable(body, mesh, root_edge):
                out_root_spec = (P("shard", None)
                                 if root_edge.sharding == SHARDED else P())
                return shard_map_compat(body, mesh=mesh,
                                        in_specs=(P("shard", None),),
                                        out_specs=(out_root_spec, P()))
        """
        found = [f for f in lint(src, MeshSpecRule(),
                                 "m3_tpu/parallel/compile.py")
                 if f.rule == "unannotated-out-sharding"]
        assert found == []

    def test_out_spec_annotation_not_required_outside_compile(self):
        src = """
            import jax
            from jax.sharding import PartitionSpec as P

            def shard_map_compat(fn, *, mesh, in_specs, out_specs):
                return fn

            def build(mesh):
                def local(rows):
                    return rows

                return shard_map_compat(local, mesh=mesh,
                                        in_specs=(P("shard"),),
                                        out_specs=(P("shard"),))
        """
        assert lint(src, MeshSpecRule(), "m3_tpu/parallel/ingest.py") == []

    def test_suppression_silences(self):
        src = """
            import jax
            from jax.sharding import Mesh, PartitionSpec as P
            import numpy as np

            def make(devs):
                return Mesh(np.asarray(devs), ("shard",))

            def fan_in(part):
                # cross-module mesh carries this axis
                # m3lint: disable=mesh-axis-unbound
                return jax.lax.psum(part, "stage")
        """
        assert lint(src, MeshSpecRule(), "m3_tpu/parallel/q.py") == []


class TestHostSyncInPlanRound16:
    """host-sync-in-plan's widened scope: the SubqueryFunc/RankAgg
    lowering helpers PR 16 added (`_range_body`, `_sub_gather`) are
    lowering surface too."""

    def test_flags_sync_in_range_body(self):
        src = """
            import numpy as np
            import jax

            def _range_body(ctx, f, ins):
                adj = ins["diff"][0]
                host = np.asarray(adj)
                return host
        """
        from m3_tpu.analysis.obs_rules import HostSyncInPlanRule
        found = lint(src, HostSyncInPlanRule(), "m3_tpu/parallel/compile.py")
        assert rule_ids(found) == ["host-sync-in-plan"]

    def test_flags_item_in_sub_gather(self):
        src = """
            import jax.numpy as jnp
            import jax

            def _sub_gather(arr, cols, fill):
                first = cols[0].item()
                return arr[:, jnp.maximum(cols, 0)], first
        """
        from m3_tpu.analysis.obs_rules import HostSyncInPlanRule
        found = lint(src, HostSyncInPlanRule(), "m3_tpu/parallel/compile.py")
        assert rule_ids(found) == ["host-sync-in-plan"]

    def test_pure_jnp_helpers_pass(self):
        src = """
            import jax.numpy as jnp
            import jax

            def _sub_gather(arr, cols, fill):
                valid = (cols >= 0)[None, :]
                g = arr[:, jnp.maximum(cols, 0)]
                return jnp.where(valid, g, fill)
        """
        from m3_tpu.analysis.obs_rules import HostSyncInPlanRule
        assert lint(src, HostSyncInPlanRule(),
                    "m3_tpu/parallel/compile.py") == []


class TestNumericFamiliesTreeGate:
    """Zero-findings gate for ONLY the numerics families — isolates a
    regression in these rules from the umbrella TestTreeGate — plus the
    --stats timing contract for the new family."""

    def test_tree_clean_under_numeric_families(self):
        rules = [DtypeDataflowRule(), SentinelTaintRule(), MeshSpecRule()]
        findings, _sup, nmods = run_paths(
            [str(REPO / "m3_tpu")], rules, program_rules=[])
        assert nmods > 100
        rendered = "\n".join(f.render() for f in findings)
        assert findings == [], f"numeric findings on the tree:\n{rendered}"

    def test_numeric_family_suppressions_are_in_use(self):
        # The documented deliberate site (compile.py baseline staging)
        # rides a justified suppression, not silence.
        rules = [DtypeDataflowRule(), SentinelTaintRule(), MeshSpecRule()]
        _findings, sup, _n = run_paths(
            [str(REPO / "m3_tpu")], rules, program_rules=[])
        assert sup >= 1

    def test_stats_timing_covers_new_family(self):
        src = "import numpy as np\n"
        mod = Module.from_source(src, "m3_tpu/ops/t.py")
        timings = {}
        run_module(mod, [DtypeDataflowRule(), SentinelTaintRule(),
                         MeshSpecRule()], timings=timings)
        assert {"numeric-dtype", "sentinel-taint",
                "mesh-spec"} <= set(timings)


class TestFindingsCacheRulesDigest:
    """The warm findings cache covers the new family: entries are keyed
    on the analyzer's own rules-source digest, so editing any rule
    module (numeric_rules.py included) invalidates the whole cache."""

    def _run_cli(self, tmp_path, target):
        import json as _json
        import subprocess as _sp

        proc = _sp.run(
            [sys.executable, "-m", "m3_tpu.analysis", str(target)],
            cwd=tmp_path, capture_output=True, text=True,
            env={**__import__("os").environ,
                 "PYTHONPATH": str(REPO)})
        return proc

    def test_cache_hit_then_rules_digest_invalidation(self, tmp_path):
        import json as _json

        target = tmp_path / "mod.py"
        target.write_text("import numpy as np\n\n\ndef f(x):\n"
                          "    return np.asarray(x)\n")
        first = self._run_cli(tmp_path, target)
        assert first.returncode == 0, first.stdout + first.stderr
        assert "(0 cached)" in first.stdout
        cache = tmp_path / ".m3lint_cache.json"
        assert cache.exists()
        second = self._run_cli(tmp_path, target)
        assert "(1 cached)" in second.stdout
        # A rules-source edit changes the digest: simulate by tampering
        # the stored digest — every entry must be recomputed, not served.
        payload = _json.loads(cache.read_text())
        assert payload["rules"]  # digest present
        payload["rules"] = "0" * 40
        cache.write_text(_json.dumps(payload))
        third = self._run_cli(tmp_path, target)
        assert "(0 cached)" in third.stdout


class TestMeshSpecReviewRegressions:
    """Review-pass regressions: name-bound edge-conditioned out_specs,
    vararg/defaulted wrapped functions."""

    def test_name_bound_edge_conditioned_out_specs_passes(self):
        # out_specs handed as a NAME bound to a tuple whose element is
        # the sanctioned IfExp — must resolve through the binding, not
        # flag the opaque name.
        src = """
            import jax
            from jax.sharding import PartitionSpec as P

            SHARDED = "shard"

            def shard_map_compat(fn, *, mesh, in_specs, out_specs):
                return fn

            def plan_executable(body, mesh, root_edge):
                out_root_spec = (P("shard", None)
                                 if root_edge.sharding == SHARDED else P())
                specs = (out_root_spec, P())
                return shard_map_compat(body, mesh=mesh,
                                        in_specs=(P("shard", None),),
                                        out_specs=specs)
        """
        found = [f for f in lint(src, MeshSpecRule(),
                                 "m3_tpu/parallel/compile.py")
                 if f.rule == "unannotated-out-sharding"]
        assert found == []

    def test_vararg_wrapped_fn_never_arity_flags(self):
        src = """
            import jax
            from jax.sharding import PartitionSpec as P

            def shard_map_compat(fn, *, mesh, in_specs, out_specs):
                return fn

            def build(mesh):
                def local(*planes):
                    return planes[0]

                return shard_map_compat(local, mesh=mesh,
                                        in_specs=(P("shard"), P("shard")),
                                        out_specs=P("shard"))
        """
        assert lint(src, MeshSpecRule(), "m3_tpu/parallel/a.py") == []

    def test_defaulted_params_tolerated_but_excess_specs_flag(self):
        src = """
            import jax
            from jax.sharding import PartitionSpec as P

            def shard_map_compat(fn, *, mesh, in_specs, out_specs):
                return fn

            def build(mesh):
                def local(values, counts=None):
                    return values

                ok = shard_map_compat(local, mesh=mesh,
                                      in_specs=(P("shard"),),
                                      out_specs=P("shard"))
                bad = shard_map_compat(local, mesh=mesh,
                                       in_specs=(P("shard"), P("shard"),
                                                 P("shard")),
                                       out_specs=P("shard"))
                return ok, bad
        """
        found = lint(src, MeshSpecRule(), "m3_tpu/parallel/a.py")
        assert rule_ids(found) == ["shard-spec-arity"]


# ===================================================================
# PR 16: concurrency-plane race analysis — thread-spawn discovery,
# lock-protection inference, the lock-free ledger, seeded PR 5/10 and
# mid-__init__ leak shapes, widened hot-loop/wall-clock scopes
# ===================================================================

from m3_tpu.analysis import race_rules  # noqa: E402
from m3_tpu.analysis.race_rules import (SharedStateRaceRule,  # noqa: E402
                                        load_ledger, protection_model)


def race_findings(srcs, ledger=None):
    """Race-family findings over synthetic sources with a CONTROLLED
    ledger (default empty: the real tree ledger must not leak into
    shape tests)."""
    idx = ProgramIndex.from_sources(
        {rel: textwrap.dedent(s) for rel, s in srcs.items()})
    rule = SharedStateRaceRule(ledger=ledger if ledger is not None else {})
    return list(rule.check_program(idx))


class TestSeededRegistryPublishBeforeAppend:
    """Historical shape 1 (the pre-fix PR 5 registry): the series index
    entry was published BEFORE the id/tags lists were appended, so a
    lock-free reader resolving through the index could read past the
    end of the lists. Reconstructed beside the fixed (append-first,
    publish-last) ordering that shipped."""

    PRE_FIX = {
        "m3_tpu/storage/registry.py": """
            import threading

            class SeriesRegistry:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._index = {}
                    self._ids = []
                    self._tags = []

                def start(self):
                    threading.Thread(target=self._writer).start()

                def _writer(self):
                    self.get_or_create(b"s", None)

                def get_or_create(self, series_id, tags):
                    with self._lock:
                        idx = len(self._ids)
                        self._index[series_id] = idx
                        self._ids.append(series_id)
                        self._tags.append(tags)
                        return idx

                def get(self, series_id):
                    return self._index.get(series_id)
        """,
    }

    def test_pre_fix_ordering_flags_unsafe_publication(self):
        found = race_findings(self.PRE_FIX)
        pubs = [f for f in found if f.rule == "unsafe-publication"]
        assert len(pubs) == 1, [f.render() for f in found]
        assert "SeriesRegistry.'_index'" in pubs[0].message
        assert "'_ids'" in pubs[0].message
        assert "append first, publish last" in pubs[0].message

    def test_fixed_append_first_publish_last_is_clean(self):
        fixed = {
            "m3_tpu/storage/registry.py": self.PRE_FIX[
                "m3_tpu/storage/registry.py"].replace(
                    """idx = len(self._ids)
                        self._index[series_id] = idx
                        self._ids.append(series_id)
                        self._tags.append(tags)""",
                    """idx = len(self._ids)
                        self._ids.append(series_id)
                        self._tags.append(tags)
                        self._index[series_id] = idx"""),
        }
        found = race_findings(fixed)
        assert [f for f in found if f.rule == "unsafe-publication"] == []

    def test_ledger_never_exempts_unsafe_publication(self):
        # Declaring the registry protocol grants the GUARD exemption
        # only; the publication ORDER stays machine-checked.
        ledger = {"SeriesRegistry._index": "publish-last",
                  "SeriesRegistry._ids": "append-only"}
        found = race_findings(self.PRE_FIX, ledger=ledger)
        assert [f.rule for f in found] == ["unsafe-publication"]


class TestSeededDegradedFlagGuard:
    """Historical shape 2 (the PR 10 sticky `_degraded` flag): the flag
    is read and cleared under the reconcile lock, but one writer set it
    lock-free — racing the guarded sites. Reconstructed beside the
    fixed (every access under the one lock) shape."""

    def _srcs(self, mark_body):
        return {
            "m3_tpu/aggregator/elem.py": f"""
                import threading

                class Elem:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._mu = threading.Lock()
                        self._degraded = False

                    def start(self):
                        threading.Thread(target=self._consume).start()

                    def _consume(self):
                        with self._lock:
                            if self._degraded:
                                return

                    def reconcile(self):
                        with self._lock:
                            self._degraded = False

                    def mark_degraded(self):
                {mark_body}
            """,
        }

    def test_lock_free_write_beside_guarded_sites_flags(self):
        found = race_findings(self._srcs("        self._degraded = True"))
        assert [f.rule for f in found] == ["unguarded-shared-write"]
        msg = found[0].message
        assert "Elem._degraded" in msg and "Elem._lock" in msg

    def test_write_under_the_wrong_lock_is_inconsistent_guard(self):
        found = race_findings(self._srcs(
            "        with self._mu:\n"
            "                            self._degraded = True"))
        assert [f.rule for f in found] == ["inconsistent-guard"]
        msg = found[0].message
        assert "Elem._lock" in msg and "Elem._mu" in msg

    def test_fixed_every_access_under_one_lock_is_clean(self):
        found = race_findings(self._srcs(
            "        with self._lock:\n"
            "                            self._degraded = True"))
        assert found == []

    def test_ledger_declares_the_protocol(self):
        found = race_findings(self._srcs("        self._degraded = True"),
                              ledger={"Elem._degraded": "sticky flag"})
        assert found == []


class TestSeededInitHandleLeak:
    """Historical shape 3: a drainer thread started mid-`__init__`,
    before the batch buffer it reads is assigned — the spawned consumer
    can observe a half-constructed instance. Reconstructed beside the
    shipped insert-queue shape (construct fully, spawn from start())."""

    LEAK = {
        "m3_tpu/storage/insert_queue.py": """
            import threading

            class InsertQueue:
                def __init__(self, shard):
                    self.shard = shard
                    self._lock = threading.Lock()
                    self._thread = threading.Thread(
                        target=self._drain, daemon=True)
                    self._thread.start()
                    self._batch = []

                def _drain(self):
                    with self._lock:
                        self._batch.clear()
        """,
    }

    def test_mid_init_spawn_before_assignment_flags(self):
        found = race_findings(self.LEAK)
        assert [f.rule for f in found] == ["unsafe-publication"]
        msg = found[0].message
        assert "self._drain" in msg and "'_batch'" in msg
        assert "spawn from start()" in msg

    def test_fixed_spawn_from_start_is_clean(self):
        fixed = {
            "m3_tpu/storage/insert_queue.py": """
                import threading

                class InsertQueue:
                    def __init__(self, shard):
                        self.shard = shard
                        self._lock = threading.Lock()
                        self._batch = []
                        self._thread = threading.Thread(
                            target=self._drain, daemon=True)

                    def start(self):
                        self._thread.start()

                    def _drain(self):
                        with self._lock:
                            self._batch.clear()
            """,
        }
        assert race_findings(fixed) == []

    def test_handoff_escape_before_assignment_flags(self):
        # The non-thread escape: `self` handed to a foreign registry
        # before __init__ finishes.
        srcs = {
            "m3_tpu/msg/consumer.py": """
                class Consumer:
                    def __init__(self, registry):
                        registry.register(self)
                        self._queue = []
            """,
        }
        found = race_findings(srcs)
        assert [f.rule for f in found] == ["unsafe-publication"]
        assert "escapes half-constructed" in found[0].message


class TestRacyCheckThenAct:
    """Rule 4: a read-test-write of a shared attr with no lock spanning
    the test and the act."""

    def _srcs(self, get_body):
        return {
            "m3_tpu/storage/cache.py": f"""
                import threading

                class Cache:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._m = {{}}

                    def start(self):
                        threading.Thread(target=self._work).start()

                    def _work(self):
                        self.get(b"k")

                    def get(self, k):
                {get_body}

                    def size(self):
                        return len(self._m)
            """,
        }

    UNLOCKED = """        if k not in self._m:
                            self._m[k] = 1
                        return self._m[k]"""
    LOCKED = """        with self._lock:
                            if k not in self._m:
                                self._m[k] = 1
                            return self._m[k]"""

    def test_unlocked_test_then_store_flags(self):
        found = race_findings(self._srcs(self.UNLOCKED))
        assert [f.rule for f in found] == ["racy-check-then-act"]
        assert "Cache._m" in found[0].message

    def test_lock_spanning_test_and_act_is_clean(self):
        assert race_findings(self._srcs(self.LOCKED)) == []

    def test_ledger_declared_single_flight_passes(self):
        found = race_findings(self._srcs(self.UNLOCKED),
                              ledger={"Cache._m": "idempotent insert"})
        assert found == []


class TestLockFreeLedger:
    def test_parse_idents_and_invariants(self, tmp_path):
        p = tmp_path / "ledger.txt"
        p.write_text("# header comment\n"
                     "\n"
                     "Foo._bar  # sticky flag: set once\n"
                     "Baz.q\n")
        got = load_ledger(p)
        assert got == {"Foo._bar": "sticky flag: set once", "Baz.q": ""}

    def test_missing_file_is_empty(self, tmp_path):
        assert load_ledger(tmp_path / "absent.txt") == {}

    def test_tree_ledger_entries_carry_invariants(self):
        # The review contract: every declared attr has a Class.attr
        # identity and a non-empty one-line invariant.
        ledger = load_ledger()
        assert ledger  # the tree declares its lock-free protocols
        for ident, reason in ledger.items():
            cls, _, attr = ident.partition(".")
            assert cls and attr, ident
            assert reason, f"{ident} has no invariant line"


class TestRaceFamilyTreeGate:
    """Zero-findings gate for ONLY the race family, against the REAL
    tree ledger — isolates a regression in these rules (or an undeclared
    new race) from the umbrella TestTreeGate."""

    def test_tree_clean_under_race_family(self):
        findings, _sup, nmods = run_paths(
            [str(REPO / "m3_tpu")], [],
            program_rules=[SharedStateRaceRule()])
        assert nmods > 100
        rendered = "\n".join(f.render() for f in findings)
        assert findings == [], f"race findings on the tree:\n{rendered}"

    def test_protection_model_is_populated(self):
        model = protection_model(str(REPO / "m3_tpu"))
        # the witness acceptance surface: dozens of attrs with an
        # inferred protecting lock, named in Class.attr form
        assert len(model) >= 20
        for ident, locks in model.items():
            assert "." in ident and locks, (ident, locks)

    def test_stats_timing_covers_the_race_family(self):
        from m3_tpu.analysis.core import run_program

        srcs = {"m3_tpu/ops/t.py": "X = 1\n"}
        idx = ProgramIndex.from_sources(srcs)
        timings = {}
        run_program(list(idx.modules.values()),
                    program_rules=[SharedStateRaceRule(ledger={})],
                    timings=timings)
        assert "shared-state-race" in timings


class TestRulesDigestCoversLedger:
    def test_ledger_edit_changes_the_digest(self):
        # The findings cache keys on the analyzer digest; the lock-free
        # ledger is an INPUT to the race family, so a ledger edit must
        # invalidate the cache exactly like a rule-source edit.
        from m3_tpu.analysis.__main__ import _rules_digest

        before = _rules_digest()
        probe = (REPO / "m3_tpu" / "analysis" /
                 "zz_digest_probe_test.txt")
        try:
            probe.write_text("Probe._x  # test entry\n")
            assert _rules_digest() != before
        finally:
            probe.unlink()
        assert _rules_digest() == before


class TestWidenedRuleScopes:
    """hot-loop-under-lock and wall-clock-latency now cover parallel/
    and testing/ — the harness and mesh planes hold locks and measure
    latency too."""

    HOT_LOOP = """
        import threading

        class Collector:
            def __init__(self):
                self._lock = threading.Lock()

            def absorb(self, items):
                with self._lock:
                    for sid, tags in items:
                        self._terms.setdefault(sid, []).append(tags)
    """

    WALL_DELTA = """
        import time

        def handle(fn):
            t0 = time.time()
            fn()
            return time.time() - t0
    """

    def test_hot_loop_flags_in_parallel_and_testing(self):
        for rel in ("m3_tpu/parallel/mod.py", "m3_tpu/testing/mod.py"):
            found = lint(self.HOT_LOOP, HotLoopUnderLockRule(), rel)
            assert rule_ids(found) == ["hot-loop-under-lock"], rel

    def test_wall_clock_flags_in_parallel_and_testing(self):
        for rel in ("m3_tpu/parallel/mod.py", "m3_tpu/testing/mod.py"):
            found = lint(self.WALL_DELTA, WallClockLatencyRule(), rel)
            assert rule_ids(found) == ["wall-clock-latency"], rel

    def test_unlisted_dirs_stay_out_of_scope(self):
        assert lint(self.HOT_LOOP, HotLoopUnderLockRule(),
                    "m3_tpu/tools/mod.py") == []
        assert lint(self.WALL_DELTA, WallClockLatencyRule(),
                    "m3_tpu/tools/mod.py") == []


class TestUnguardedPallasDispatch:
    """unguarded-pallas-dispatch: pl.pallas_call must forward a builder
    `interpret` parameter and the module must declare an existing
    _PALLAS_ORACLE parity-test pointer."""

    CLEAN = """
        import jax
        from jax.experimental import pallas as pl

        _PALLAS_ORACLE = "tests/test_temporal.py"

        def _build(n, interpret):
            return pl.pallas_call(_kernel, interpret=interpret)
    """

    def test_clean_builder_passes(self):
        assert lint(self.CLEAN, UnguardedPallasDispatchRule()) == []

    def test_missing_interpret_kwarg_flags(self):
        src = self.CLEAN.replace(", interpret=interpret", "")
        found = lint(src, UnguardedPallasDispatchRule())
        assert rule_ids(found) == ["unguarded-pallas-dispatch"]
        assert "interpret" in found[0].message

    def test_hardcoded_interpret_flags(self):
        for const in ("False", "True"):
            src = self.CLEAN.replace("interpret=interpret",
                                     f"interpret={const}")
            found = lint(src, UnguardedPallasDispatchRule())
            assert rule_ids(found) == ["unguarded-pallas-dispatch"], const
            assert "hard-codes" in found[0].message

    def test_interpret_not_from_builder_param_flags(self):
        src = """
            import jax
            from jax.experimental import pallas as pl

            _PALLAS_ORACLE = "tests/test_temporal.py"
            _GLOBAL_INTERPRET = True

            def _build(n):
                return pl.pallas_call(_kernel,
                                      interpret=_GLOBAL_INTERPRET)
        """
        found = lint(src, UnguardedPallasDispatchRule())
        assert rule_ids(found) == ["unguarded-pallas-dispatch"]
        assert "builder parameter" in found[0].message

    def test_missing_oracle_decl_flags(self):
        src = self.CLEAN.replace(
            '_PALLAS_ORACLE = "tests/test_temporal.py"', "")
        found = lint(src, UnguardedPallasDispatchRule())
        assert rule_ids(found) == ["unguarded-pallas-dispatch"]
        assert "_PALLAS_ORACLE" in found[0].message

    def test_nonexistent_oracle_path_flags(self):
        src = self.CLEAN.replace("tests/test_temporal.py",
                                 "tests/test_gone_forever.py")
        found = lint(src, UnguardedPallasDispatchRule())
        assert rule_ids(found) == ["unguarded-pallas-dispatch"]
        assert "does not" in found[0].message

    def test_jit_wrapped_pallas_call_sees_through(self):
        # the _build_hash idiom: jax.jit(pl.pallas_call(...))
        src = """
            import jax
            from jax.experimental import pallas as pl

            _PALLAS_ORACLE = "tests/test_temporal.py"

            def _build(n, interpret):
                return jax.jit(pl.pallas_call(_kernel, interpret=interpret))
        """
        assert lint(src, UnguardedPallasDispatchRule()) == []

    def test_module_without_pallas_call_is_ignored(self):
        src = """
            import jax

            def f(x):
                return jax.jit(lambda y: y)(x)
        """
        assert lint(src, UnguardedPallasDispatchRule()) == []

    def test_repo_pallas_modules_conform(self):
        for rel in ("m3_tpu/ops/pallas_window.py",
                    "m3_tpu/ops/pallas_codec.py"):
            path = REPO / rel
            mod = Module(str(path), rel, path.read_text())
            findings, _ = run_module(mod, [UnguardedPallasDispatchRule()])
            assert findings == [], rel


class TestUnclassifiedDeviceDispatch:
    """unclassified-device-dispatch: broad except around a device
    dispatch site (jit-builder call, traced fn, pallas_call) must
    classify into the ComputeError taxonomy or re-raise."""

    # the exact pre-guard shape: a jit-builder result dispatched under
    # `except Exception: return None` — a device OOM absorbed here never
    # reaches the breaker/quarantine/telemetry plane.
    SEEDED = """
        import jax

        def _build(n):
            return jax.jit(lambda x: x * n)

        def execute(x, n):
            fn = _build(n)
            try:
                return fn(x)
            except Exception:
                return None
    """

    def test_seeded_builder_dispatch_flags(self):
        found = lint(self.SEEDED, UnclassifiedDeviceDispatchRule(),
                     "m3_tpu/parallel/mod.py")
        assert rule_ids(found) == ["unclassified-device-dispatch"]
        assert "ComputeError taxonomy" in found[0].message
        assert "guard.dispatch" in found[0].message

    def test_direct_builder_call_flags(self):
        src = """
            import jax

            def _build(n):
                return jax.jit(lambda x: x * n)

            def execute(x, n):
                try:
                    return _build(n)(x)
                except Exception:
                    return None
        """
        found = lint(src, UnclassifiedDeviceDispatchRule())
        assert rule_ids(found) == ["unclassified-device-dispatch"]

    def test_bare_except_around_traced_fn_flags(self):
        src = """
            import jax

            def _kernel(x):
                return x + 1

            _fast = jax.jit(_kernel)

            def run(x):
                try:
                    return _kernel(x)
                except:
                    return None
        """
        found = lint(src, UnclassifiedDeviceDispatchRule())
        assert rule_ids(found) == ["unclassified-device-dispatch"]

    def test_classifying_handler_is_clean(self):
        # the guard-seam shape: broad handler funnels through classify()
        # and re-raises the unclassifiable — the canonical negative.
        src = """
            import jax
            from ..parallel import guard

            def _build(n):
                return jax.jit(lambda x: x * n)

            def execute(x, n):
                fn = _build(n)
                try:
                    return fn(x)
                except Exception as exc:
                    err = guard.classify(exc, "plan")
                    if err is None:
                        raise
                    return err
        """
        assert lint(src, UnclassifiedDeviceDispatchRule()) == []

    def test_reraising_handler_is_clean(self):
        src = self.SEEDED.replace("return None", "raise")
        assert lint(src, UnclassifiedDeviceDispatchRule()) == []

    def test_taxonomy_raise_is_clean(self):
        src = self.SEEDED.replace(
            "return None", 'raise KernelFault("plan", "boom")')
        assert lint(src, UnclassifiedDeviceDispatchRule()) == []

    def test_narrow_handler_is_out_of_scope(self):
        src = self.SEEDED.replace("except Exception:",
                                  "except ValueError:")
        assert lint(src, UnclassifiedDeviceDispatchRule()) == []

    def test_broad_except_without_dispatch_is_clean(self):
        src = """
            import jax

            def parse(raw):
                try:
                    return int(raw)
                except Exception:
                    return 0
        """
        assert lint(src, UnclassifiedDeviceDispatchRule()) == []

    def test_out_of_scope_dirs_are_skipped(self):
        found = lint(self.SEEDED, UnclassifiedDeviceDispatchRule(),
                     "m3_tpu/coordinator/mod.py")
        assert found == []

    def test_guard_seam_itself_is_clean(self):
        rel = "m3_tpu/parallel/guard.py"
        path = REPO / rel
        mod = Module(str(path), rel, path.read_text())
        findings, _ = run_module(mod, [UnclassifiedDeviceDispatchRule()])
        assert findings == []

    def test_tree_has_zero_findings(self):
        findings, _sup, nmods = run_paths(
            [str(REPO / "m3_tpu")], [UnclassifiedDeviceDispatchRule()],
            program_rules=[])
        assert nmods > 100
        assert findings == []
