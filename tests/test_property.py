"""Property-based tests (reference test tier 2, TESTING.md: gopter
generative tests — commitlog read/write roundtrip prop
(persist/fs/commitlog/read_write_prop_test.go), encoding roundtrip
(m3tsz/roundtrip_test.go), serialize lifecycle
(x/serialize/decoder_lifecycle_prop_test.go), index query proptest
(m3ninx/search/proptest), shard race prop
(storage/shard_race_prop_test.go — Python threads under the GIL still
exercise interleaving on the lock boundaries)."""

import threading

import numpy as np
import pytest

# hypothesis is an optional dev dependency: without it this tier-2 module
# must SKIP at collection, not error the whole collection pass.
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from m3_tpu.ops import ref_codec
from m3_tpu.utils import serialize
from m3_tpu.utils import xtime

S = xtime.SECOND
T0 = 1_600_000_000 * S


# --------------------------------------------------------------- codec

@st.composite
def series_points(draw):
    n = draw(st.integers(min_value=1, max_value=200))
    # Timestamps in TICKS (the codec encodes unit-scaled ticks; callers pick
    # the xtime unit): regular step with jitter, strictly increasing.
    base_step = draw(st.sampled_from([1, 10, 60]))
    jitter = draw(st.lists(
        st.integers(min_value=0, max_value=max(1, base_step // 2)),
        min_size=n, max_size=n))
    ts = np.cumsum(np.full(n, base_step) + np.array(jitter)) + T0 // S
    kind = draw(st.sampled_from(["int_like", "float", "mixed", "special"]))
    if kind == "int_like":
        vals = draw(st.lists(st.integers(min_value=-10**9, max_value=10**9),
                             min_size=n, max_size=n))
        values = np.array(vals, dtype=np.float64)
    elif kind == "float":
        vals = draw(st.lists(
            st.floats(min_value=-1e12, max_value=1e12,
                      allow_nan=False, allow_infinity=False),
            min_size=n, max_size=n))
        values = np.array(vals)
    elif kind == "mixed":
        vals = draw(st.lists(
            st.one_of(st.integers(min_value=-1000, max_value=1000),
                      st.floats(min_value=-1e6, max_value=1e6,
                                allow_nan=False, allow_infinity=False)),
            min_size=n, max_size=n))
        values = np.array([float(v) for v in vals])
    else:
        pool = [0.0, -0.0, 1e-300, -1e300, np.inf, -np.inf,
                float(np.finfo(np.float64).max), 1.5e-5]
        vals = draw(st.lists(st.sampled_from(pool), min_size=n, max_size=n))
        values = np.array(vals)
    return ts.astype(np.int64), values


class TestCodecRoundtripProperty:
    @settings(max_examples=60, deadline=None)
    @given(series_points())
    def test_roundtrip_bit_exact(self, pts):
        ts, values = pts
        blk = ref_codec.encode(ts, values)
        t2, v2 = ref_codec.decode(blk)
        np.testing.assert_array_equal(t2, ts)
        # Bit-exact float64 roundtrip (the codec's core invariant).
        np.testing.assert_array_equal(
            np.asarray(v2).view(np.uint64), values.view(np.uint64))


# --------------------------------------------------------------- serialize

class TestSerializeProperty:
    @settings(max_examples=60, deadline=None)
    @given(st.dictionaries(
        st.binary(min_size=0, max_size=40), st.binary(min_size=0, max_size=40),
        max_size=20))
    def test_tags_roundtrip(self, tags):
        assert serialize.decode_tags(serialize.encode_tags(tags)) == tags

    @settings(max_examples=60, deadline=None)
    @given(st.dictionaries(st.binary(min_size=1, max_size=10),
                           st.binary(max_size=10), max_size=6),
           st.integers(min_value=0, max_value=100))
    def test_truncation_always_detected(self, tags, cut):
        buf = serialize.encode_tags(tags)
        if cut == 0 or cut >= len(buf):
            return
        truncated = buf[:-cut]
        try:
            out = serialize.decode_tags(truncated)
        except serialize.TagEncodeError:
            return  # detected, good
        # If it decoded, it must NOT equal the original (no silent alias).
        assert out != tags


# --------------------------------------------------------------- commitlog

class TestCommitlogProperty:
    @settings(max_examples=25, deadline=None)
    @given(st.lists(
        st.tuples(st.sampled_from([b"ns1", b"ns2"]),
                  st.binary(min_size=1, max_size=12),
                  st.integers(min_value=0, max_value=2**40),
                  st.floats(allow_nan=False, allow_infinity=False,
                            width=64)),
        min_size=0, max_size=120))
    def test_write_replay_roundtrip(self, entries):
        import tempfile

        from m3_tpu.persist import commitlog as cl

        d = tempfile.mkdtemp(prefix="m3tpu-clprop-")
        log = cl.CommitLog(d, strategy=cl.Strategy.WRITE_WAIT)
        for ns, sid, t, v in entries:
            log.write(ns, sid, t, v)
        log.close()
        replayed = list(cl.replay(d))
        assert len(replayed) == len(entries)
        for (ns, sid, t, v), (rns, rsid, rt, rv) in zip(entries, replayed):
            assert (ns, sid, t) == (rns, rsid, rt)
            assert np.float64(v).view(np.uint64) == np.float64(rv).view(np.uint64)


# --------------------------------------------------------------- index

class TestIndexQueryProperty:
    @settings(max_examples=30, deadline=None)
    @given(st.data())
    def test_boolean_search_matches_bruteforce(self, data):
        from m3_tpu.index import query as iq
        from m3_tpu.index.segment import Document, MutableSegment, execute

        fields = [b"a", b"b", b"c"]
        values = [b"x", b"y", b"z"]
        n_docs = data.draw(st.integers(min_value=1, max_value=30))
        docs = []
        seg = MutableSegment()
        for i in range(n_docs):
            tags = {
                f: data.draw(st.sampled_from(values), label=f"doc{i}.{f}")
                for f in fields
                if data.draw(st.booleans(), label=f"has{i}.{f}")
            }
            sid = b"doc-%d" % i
            docs.append((sid, tags))
            seg.insert(Document(sid, tuple(sorted(tags.items()))))

        def rand_query(depth=0):
            kind = data.draw(st.sampled_from(
                ["term", "term", "regexp", "conj", "disj", "neg"]
                if depth < 2 else ["term", "regexp"]))
            if kind == "term":
                return iq.new_term(data.draw(st.sampled_from(fields)),
                                   data.draw(st.sampled_from(values)))
            if kind == "regexp":
                return iq.new_regexp(data.draw(st.sampled_from(fields)),
                                     data.draw(st.sampled_from([b"x|y", b"[yz]", b".*"])))
            if kind == "neg":
                return iq.new_negation(rand_query(depth + 1))
            parts = [rand_query(depth + 1) for _ in
                     range(data.draw(st.integers(min_value=1, max_value=3)))]
            return (iq.new_conjunction(*parts) if kind == "conj"
                    else iq.new_disjunction(*parts))

        def brute(q, tags):
            import re as _re

            if isinstance(q, iq.AllQuery):
                return True
            if isinstance(q, iq.TermQuery):
                return tags.get(q.field) == q.value
            if isinstance(q, iq.RegexpQuery):
                v = tags.get(q.field)
                return v is not None and _re.fullmatch(q.pattern, v) is not None
            if isinstance(q, iq.ConjunctionQuery):
                return all(brute(p, tags) for p in q.queries)
            if isinstance(q, iq.DisjunctionQuery):
                return any(brute(p, tags) for p in q.queries)
            if isinstance(q, iq.NegationQuery):
                return not brute(q.query, tags)
            raise AssertionError(q)

        q = rand_query()
        got = {seg.doc(p).id for p in execute(seg, q)}
        want = {sid for sid, tags in docs if brute(q, tags)}
        assert got == want


# --------------------------------------------------------------- shard race

class TestShardRace:
    def test_concurrent_writes_one_series_space(self):
        """storage/shard_race_prop_test.go analog: concurrent writers to an
        overlapping id space; every accepted write must be readable and
        series counts consistent."""
        from m3_tpu.parallel.sharding import ShardSet
        from m3_tpu.storage.database import Database
        from m3_tpu.storage.namespace import NamespaceOptions

        now = {"t": T0}
        db = Database(ShardSet(4), clock=lambda: now["t"])
        db.create_namespace(b"default", NamespaceOptions(index_enabled=False))
        n_threads, n_writes = 8, 200
        errors = []

        def writer(tid):
            try:
                for i in range(n_writes):
                    sid = b"race-%d" % ((tid * 7 + i) % 20)
                    db.write(b"default", sid, now["t"] + (i % 50) * S + tid,
                             float(tid * 1000 + i))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # All 20 series exist, each readable, total points = dedup of writes.
        total = 0
        for i in range(20):
            t, v = db.read(b"default", b"race-%d" % i, 0, now["t"] + 3600 * S)
            assert len(t) == len(np.unique(t))
            total += len(t)
        assert total > 0
