"""Carbon ingestion + graphite query language tests (reference:
src/metrics/carbon/parser.go, src/query/graphite/native builtins, the
carbon docker integration test flow: line in -> render out)."""

import json
import socket
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from m3_tpu.cluster import kv as cluster_kv
from m3_tpu.coordinator import run_embedded
from m3_tpu.coordinator.carbon_ingest import CarbonServer
from m3_tpu.index.namespace_index import NamespaceIndex
from m3_tpu.metrics import carbon
from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.query.graphite import (
    GraphiteEngine,
    parse_target,
    path_to_matchers,
    series_name,
)
from m3_tpu.storage.database import Database
from m3_tpu.storage.namespace import NamespaceOptions

S = 1_000_000_000
T0 = 1_600_000_000 * S


class TestCarbonParser:
    def test_parse_valid(self):
        assert carbon.parse_line(b"servers.web01.cpu 42.5 1600000000") == (
            b"servers.web01.cpu", 42.5, 1600000000)

    def test_parse_rejects_malformed(self):
        for bad in [b"", b"onlypath", b"a.b 1.0", b"a.b x 123",
                    b".lead 1 2", b"trail. 1 2", b"a.b nan 123"]:
            assert carbon.parse_line(bad) is None

    def test_path_tags_roundtrip(self):
        tags = carbon.path_to_tags(b"a.b.c")
        assert tags == {b"__g0__": b"a", b"__g1__": b"b", b"__g2__": b"c"}
        assert carbon.tags_to_path(tags) == b"a.b.c"


class TestPathMatchers:
    def test_literal_and_glob(self):
        ms = path_to_matchers("servers.*.cpu")
        assert ms[0].value == b"servers"
        assert ms[1].type.name == "REGEXP"
        # depth guard: no __g3__ allowed
        assert ms[-1].name == b"__g3__"

    def test_alternation(self):
        ms = path_to_matchers("servers.{web01,web02}.cpu")
        assert ms[1].matches(b"web01") and ms[1].matches(b"web02")
        assert not ms[1].matches(b"web03")


class TestTargetParser:
    def test_nested_calls(self):
        ast = parse_target('scale(sumSeries(servers.*.cpu), 0.5)')
        assert ast.func == "scale"
        assert ast.args[0].func == "sumSeries"
        assert ast.args[1].value == 0.5


@pytest.fixture
def genv():
    now = {"t": T0}
    db = Database(ShardSet(8), clock=lambda: now["t"])
    db.create_namespace(b"default", NamespaceOptions(),
                        index=NamespaceIndex(clock=lambda: now["t"]))
    c = run_embedded(db, clock=lambda: now["t"])
    yield c, db, now
    c.close()


def ingest_paths(c, now, paths_values):
    for i in range(12):
        now["t"] = T0 + i * 10 * S
        for path, base in paths_values:
            tags = carbon.path_to_tags(path)
            c.writer.write(tags, T0 + i * 10 * S, base + i)


class TestGraphiteEngine:
    def test_glob_fetch_and_sum(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"servers.web01.cpu", 10.0),
                              (b"servers.web02.cpu", 20.0),
                              (b"servers.web01.mem", 99.0)])
        eng = GraphiteEngine(c.engine.storage)
        blk = eng.render("servers.*.cpu", T0 + 30 * S, T0 + 110 * S, 10 * S)
        assert blk.n_series == 2
        blk = eng.render("sumSeries(servers.*.cpu)", T0 + 30 * S, T0 + 110 * S,
                         10 * S)
        assert blk.n_series == 1
        np.testing.assert_allclose(blk.values[0][0], 10 + 20 + 2 * 3)

    def test_alias_by_node_and_scale(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"servers.web01.cpu", 10.0)])
        eng = GraphiteEngine(c.engine.storage)
        blk = eng.render("aliasByNode(scale(servers.web01.cpu, 2), 1)",
                         T0 + 30 * S, T0 + 60 * S, 10 * S)
        assert series_name(blk.series_tags[0]) == b"web01"
        np.testing.assert_allclose(blk.values[0][0], 2 * 13.0)

    def test_group_by_node(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"dc1.web01.cpu", 1.0), (b"dc1.web02.cpu", 2.0),
                              (b"dc2.web03.cpu", 5.0)])
        eng = GraphiteEngine(c.engine.storage)
        blk = eng.render('groupByNode(*.*.cpu, 0, "sum")',
                         T0 + 30 * S, T0 + 30 * S, 10 * S)
        got = {series_name(t): v[0] for t, v in zip(blk.series_tags, blk.values)}
        assert got[b"dc1"] == (1 + 3) + (2 + 3)
        assert got[b"dc2"] == 5 + 3

    def test_per_second_and_moving_average(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"counters.reqs", 0.0)])
        eng = GraphiteEngine(c.engine.storage)
        blk = eng.render("perSecond(counters.reqs)", T0 + 30 * S, T0 + 80 * S,
                         10 * S)
        np.testing.assert_allclose(blk.values[0][1:], 0.1)  # +1 per 10s
        blk = eng.render("movingAverage(counters.reqs, 3)", T0 + 30 * S,
                         T0 + 80 * S, 10 * S)
        # the reference's moving window EXCLUDES the current point
        # (builtin_functions.go:620-666): at T0+30 (value 3) it averages
        # the three points before it — values 0, 1, 2.
        np.testing.assert_allclose(blk.values[0][0], (0 + 1 + 2) / 3)


class TestCarbonServerEndToEnd:
    def test_tcp_lines_to_graphite_render(self, genv):
        c, db, now = genv
        srv = CarbonServer(c.writer).start()
        try:
            host, _, port = srv.endpoint.rpartition(":")
            lines = []
            for i in range(6):
                lines.append(b"foo.bar.baz %f %d" % (float(i), (T0 + i * 10 * S) // S))
            now["t"] = T0 + 60 * S
            with socket.create_connection((host, int(port))) as sock:
                sock.sendall(b"\n".join(lines) + b"\nbad line\n")
            deadline = time.time() + 5
            while srv.lines_ingested < 6 and time.time() < deadline:
                time.sleep(0.02)
            assert srv.lines_ingested == 6
            assert srv.lines_malformed == 1
            # Render through the HTTP API.
            q = urllib.parse.urlencode(
                {"target": "foo.bar.baz", "from": T0 / S, "until": T0 / S + 50,
                 "step": "10"})
            with urllib.request.urlopen(
                    f"{c.endpoint}/api/v1/graphite/render?{q}") as resp:
                out = json.loads(resp.read())
            assert out[0]["target"] == "foo.bar.baz"
            vals = [v for v, _ in out[0]["datapoints"] if v is not None]
            assert vals == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
        finally:
            srv.close()


class TestExtendedBuiltins:
    """Appendix builtins (builtin_functions.go coverage expansion)."""

    @pytest.fixture
    def env(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"apps.api.req", 10.0),
                              (b"apps.api.err", 1.0),
                              (b"apps.db.req", 100.0)])
        return GraphiteEngine(c.engine.storage), T0 + 30 * S, T0 + 110 * S

    def render(self, env, target):
        eng, start, end = env
        return eng.render(target, start, end, 10 * S)

    def test_alias_sub_and_by_metric(self, env):
        blk = self.render(env, 'aliasSub(apps.api.req, "apps\\.", "svc.")')
        assert series_name(blk.series_tags[0]) == b"svc.api.req"
        blk = self.render(env, "aliasByMetric(apps.*.req)")
        assert {series_name(t) for t in blk.series_tags} == {b"req"}

    def test_substr(self, env):
        blk = self.render(env, "substr(apps.api.req, 1, 2)")
        assert series_name(blk.series_tags[0]) == b"api"

    def test_math_transforms(self, env):
        v0 = self.render(env, "apps.api.req").values
        assert np.allclose(self.render(env, "scaleToSeconds(apps.api.req, 20)").values,
                           v0 * 2, equal_nan=True)
        assert np.allclose(self.render(env, "invert(apps.api.req)").values,
                           1.0 / v0, equal_nan=True)
        assert np.allclose(self.render(env, "pow(apps.api.req, 2)").values,
                           v0 ** 2, equal_nan=True)
        assert np.allclose(self.render(env, "squareRoot(apps.api.req)").values,
                           np.sqrt(v0), equal_nan=True)
        assert np.allclose(self.render(env, "logarithm(apps.api.req)").values,
                           np.log10(v0), equal_nan=True)

    def test_time_shift(self, env):
        eng, start, end = env
        shifted = eng.render('timeShift(apps.api.req, "30s")', start, end, 10 * S)
        plain = eng.render("apps.api.req", start - 30 * S, end - 30 * S, 10 * S)
        np.testing.assert_allclose(shifted.values, plain.values)
        assert shifted.meta.start_ns == start

    def test_transform_null_and_is_non_null(self, env):
        eng, start, end = env
        blk = eng.render("transformNull(apps.api.req, -1)", start, end + 60 * S, 10 * S)
        assert (blk.values[0] == -1).any()  # beyond ingested range -> filled
        nn = eng.render("isNonNull(apps.api.req)", start, end + 60 * S, 10 * S)
        assert set(np.unique(nn.values)) <= {0.0, 1.0}

    def test_remove_value_bounds(self, env):
        v0 = self.render(env, "apps.api.req").values
        hi = self.render(env, "removeAboveValue(apps.api.req, 15)").values
        assert np.isnan(hi[v0 > 15]).all()
        lo = self.render(env, "removeBelowValue(apps.api.req, 15)").values
        assert np.isnan(lo[v0 < 15]).all()

    def test_integral_and_offset_to_zero(self, env):
        v0 = self.render(env, "apps.api.req").values
        integ = self.render(env, "integral(apps.api.req)").values
        np.testing.assert_allclose(integ[0, -1], np.nansum(v0))
        z = self.render(env, "offsetToZero(apps.api.req)").values
        assert np.nanmin(z) == 0.0

    def test_filters_and_tops(self, env):
        blk = self.render(env, "maximumAbove(apps.*.req, 50)")
        assert blk.n_series == 1
        assert series_name(blk.series_tags[0]) == b"apps.db.req"
        blk = self.render(env, "currentBelow(apps.*.req, 50)")
        assert blk.n_series == 1
        blk = self.render(env, "highestAverage(apps.*.req, 1)")
        assert series_name(blk.series_tags[0]) == b"apps.db.req"
        blk = self.render(env, "lowestCurrent(apps.*.req, 1)")
        assert series_name(blk.series_tags[0]) == b"apps.api.req"

    def test_sorts(self, env):
        blk = self.render(env, "sortByTotal(apps.*.req)")
        assert series_name(blk.series_tags[0]) == b"apps.db.req"
        blk = self.render(env, "sortByMinima(apps.*.req)")
        assert series_name(blk.series_tags[0]) == b"apps.api.req"

    def test_percentiles(self, env):
        v0 = self.render(env, "apps.api.req").values
        npct = self.render(env, "nPercentile(apps.api.req, 50)").values
        assert np.allclose(npct[0], np.percentile(v0[0][np.isfinite(v0[0])], 50))
        pos = self.render(env, "percentileOfSeries(apps.*.req, 100)").values
        hi = self.render(env, "apps.db.req").values
        np.testing.assert_allclose(pos, hi, equal_nan=True)

    def test_moving_family(self, env):
        ms = self.render(env, "movingSum(apps.api.req, 3)").values
        v0 = self.render(env, "apps.api.req").values
        assert ms.shape == v0.shape
        mm = self.render(env, "movingMedian(apps.api.req, 3)").values
        assert np.isfinite(mm).any()

    def test_series_combinators(self, env):
        req = self.render(env, "apps.api.req").values
        err = self.render(env, "apps.api.err").values
        diff = self.render(env, "diffSeries(apps.api.req, apps.api.err)").values
        np.testing.assert_allclose(diff[0], req[0] - err[0], equal_nan=True)
        div = self.render(env, "divideSeries(apps.api.err, apps.api.req)").values
        np.testing.assert_allclose(div[0], err[0] / req[0], equal_nan=True)
        rng = self.render(env, "rangeOfSeries(apps.*.req)").values
        assert (rng >= 0).all()
        cnt = self.render(env, "countSeries(apps.*.req)").values
        assert (cnt == 2.0).all()

    def test_as_percent(self, env):
        pct = self.render(env, "asPercent(apps.*.req)").values
        np.testing.assert_allclose(pct.sum(axis=0), 100.0)

    def test_wildcards_grouping(self, env):
        blk = self.render(env, "sumSeriesWithWildcards(apps.*.req, 1)")
        assert blk.n_series == 1
        assert series_name(blk.series_tags[0]) == b"apps.req"
        blk = self.render(env, 'groupByNodes(apps.*.*, "sum", 1)')
        names = {series_name(t) for t in blk.series_tags}
        assert names == {b"api", b"db"}

    def test_group_constant_threshold_stacked(self, env):
        blk = self.render(env, "group(apps.api.req, apps.db.req)")
        assert blk.n_series == 2
        cl = self.render(env, "constantLine(5)")
        assert (cl.values == 5.0).all()
        th = self.render(env, 'threshold(9, "nine")')
        assert series_name(th.series_tags[0]) == b"nine"
        st = self.render(env, "stacked(sortByName(apps.*.req))")
        v_api = self.render(env, "apps.api.req").values[0]
        v_db = self.render(env, "apps.db.req").values[0]
        np.testing.assert_allclose(st.values[1], v_api + v_db, equal_nan=True)

    def test_delay_and_changed(self, env):
        d = self.render(env, "delay(apps.api.req, 2)").values
        v0 = self.render(env, "apps.api.req").values
        np.testing.assert_allclose(d[0, 2:], v0[0, :-2], equal_nan=True)
        ch = self.render(env, "changed(apps.api.req)").values
        assert (ch[0, 1:][np.isfinite(v0[0, 1:])] == 1.0).all()


class TestRound4Builtins:
    """This round's additions: presentation/synthesis functions, interval
    reductions, and the Holt-Winters family (builtin_functions.go parity)."""

    @pytest.fixture
    def env(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"apps.api.req", 10.0),
                              (b"apps.api.err", 1.0),
                              (b"apps.db.req", 100.0)])
        return GraphiteEngine(c.engine.storage), T0 + 30 * S, T0 + 110 * S

    def render(self, env, target):
        eng, start, end = env
        return eng.render(target, start, end, 10 * S)

    def test_time_identity_random(self, env):
        eng, start, end = env
        blk = self.render(env, 'timeFunction("t")')
        np.testing.assert_allclose(blk.values[0], blk.meta.times() / S)
        assert series_name(blk.series_tags[0]) == b"t"
        blk2 = self.render(env, 'identity("x")')
        np.testing.assert_allclose(blk2.values, blk.values)
        r1 = self.render(env, 'randomWalkFunction("r")')
        r2 = self.render(env, 'randomWalk("r")')
        np.testing.assert_allclose(r1.values, r2.values)  # name-seeded
        assert (np.abs(r1.values) <= 0.5).all()

    def test_dashed_and_legend_value(self, env):
        blk = self.render(env, "dashed(apps.api.req)")
        assert series_name(blk.series_tags[0]) == \
            b"dashed(apps.api.req, 5.000)"
        v0 = self.render(env, "apps.api.req").values
        blk = self.render(env, 'legendValue(apps.api.req, "max")')
        expected = b"apps.api.req (max: %.3f)" % np.nanmax(v0)
        assert series_name(blk.series_tags[0]) == expected

    def test_cacti_style(self, env):
        blk = self.render(env, "cactiStyle(apps.*.req)")
        names = sorted(series_name(t) for t in blk.series_tags)
        assert all(b"Current:" in n and b"Max:" in n and b"Min:" in n
                   for n in names)
        # column alignment: equal lengths
        assert len({len(n) for n in names}) == 1

    def test_fallback_and_remove_empty(self, env):
        blk = self.render(env, "fallbackSeries(apps.nothing.req, apps.db.req)")
        assert blk.n_series == 1
        assert series_name(blk.series_tags[0]) == b"apps.db.req"
        blk = self.render(env, "fallbackSeries(apps.db.req, apps.api.req)")
        assert series_name(blk.series_tags[0]) == b"apps.db.req"
        eng, start, end = env
        # beyond the ingested window every series is empty
        blk = eng.render("removeEmptySeries(apps.*.req)", end + 3600 * S,
                         end + 3700 * S, 10 * S)
        assert blk.n_series == 0
        blk = self.render(env, "removeEmptySeries(apps.*.req)")
        assert blk.n_series == 2

    def test_most_deviant(self, env):
        blk = self.render(env, "mostDeviant(apps.*.*, 1)")
        # all series ramp identically (+1/step) except err starts lower —
        # equal stddev; stable sort keeps first. Add a flat line to compare.
        assert blk.n_series == 1
        blk = self.render(env, "mostDeviant(group(apps.api.req, constantLine(5)), 1)")
        assert series_name(blk.series_tags[0]) == b"apps.api.req"

    def test_aggregate_line(self, env):
        v0 = self.render(env, "apps.api.req").values
        blk = self.render(env, 'aggregateLine(apps.api.req, "max")')
        np.testing.assert_allclose(blk.values[0], np.nanmax(v0))
        assert series_name(blk.series_tags[0]).startswith(b"aggregateLine(")

    def test_hitcount(self, env):
        eng, start, end = env
        blk = eng.render('hitcount(apps.api.req, "30s")', start, start + 90 * S,
                         10 * S)
        assert blk.meta.step_ns == 30 * S
        # every step contributes value*10s into the bucket containing its
        # start; the end-inclusive grid point at t=end starts outside all
        # buckets and is dropped
        plain = eng.render("apps.api.req", start, start + 90 * S, 10 * S)
        total_hits = np.nansum(plain.values[:, :-1]) * 10
        np.testing.assert_allclose(np.nansum(blk.values), total_hits)
        first_bucket = np.nansum(plain.values[:, :3]) * 10
        np.testing.assert_allclose(blk.values[0, 0], first_bucket)

    def test_sustained_above_below(self, env):
        eng, start, end = env
        # req ramps 13..21 over the window; threshold 15 holds from the 3rd
        # point on. With a 30s interval (3 steps) the first 2 qualifying
        # points flatten to the zero line.
        blk = eng.render('sustainedAbove(apps.api.req, 15, "30s")',
                         start, start + 80 * S, 10 * S)
        v = blk.values[0]
        plain = eng.render("apps.api.req", start, start + 80 * S, 10 * S).values[0]
        qualified = plain >= 15
        run = 0
        for i in range(v.size):
            run = run + 1 if qualified[i] else 0
            if run >= 3:
                assert v[i] == plain[i]
            else:
                assert v[i] == 0.0  # 15 - |15|
        blk = eng.render('sustainedBelow(apps.api.req, 14, "20s")',
                         start, start + 80 * S, 10 * S)
        # run starts at point 0 (13<=14) but only sustains 20s at point 1
        assert (blk.values[0][:2] == [28.0, 14.0]).all()
        assert (blk.values[0][2:] == 28.0).all()

    def test_weighted_average(self, env):
        # weight req by err per app node 1: only 'api' has both
        blk = self.render(env,
                          "weightedAverage(apps.*.req, apps.*.err, 1)")
        assert blk.n_series == 1
        req = self.render(env, "apps.api.req").values[0]
        err = self.render(env, "apps.api.err").values[0]
        with np.errstate(invalid="ignore"):
            expected = np.where(err != 0, req * err / err, np.nan)
        np.testing.assert_allclose(blk.values[0], expected, equal_nan=True)

    def test_holt_winters_family(self, env):
        eng, start, end = env
        fc = eng.render("holtWintersForecast(apps.api.req)", start, end, 10 * S)
        assert fc.n_series == 1
        assert series_name(fc.series_tags[0]) == \
            b"holtWintersForecast(apps.api.req)"
        assert fc.values.shape == (1, fc.meta.steps)
        bands = eng.render("holtWintersConfidenceBands(apps.api.req, 3)",
                           start, end, 10 * S)
        assert bands.n_series == 2
        lower, upper = bands.values
        finite = np.isfinite(lower) & np.isfinite(upper)
        assert (upper[finite] >= lower[finite]).all()
        ab = eng.render("holtWintersAberration(apps.api.req, 3)",
                        start, end, 10 * S)
        assert ab.n_series == 1
        assert np.isfinite(ab.values).all()
        # aberration == excursion outside the bands, 0 inside/NaN
        plain = eng.render("apps.api.req", start, end, 10 * S).values[0]
        expected = np.zeros_like(plain)
        over = np.isfinite(plain) & np.isfinite(upper) & (plain > upper)
        under = np.isfinite(plain) & np.isfinite(lower) & (plain < lower)
        expected[over] = (plain - upper)[over]
        expected[under] = (plain - lower)[under]
        np.testing.assert_allclose(ab.values[0], expected)


class TestBuiltinConformance:
    """Exact-value sweep over the builtins no other test exercises
    (reference semantics: src/query/graphite/native/builtin_functions.go).
    Window: T0+30..T0+60 @10s over t.a=[13..16], t.b=[23..26], t.c=[8..11]."""

    @pytest.fixture
    def teng(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"t.a", 10.0), (b"t.b", 20.0), (b"t.c", 5.0)])
        eng = GraphiteEngine(c.engine.storage)
        render = lambda target: eng.render(  # noqa: E731
            target, T0 + 30 * S, T0 + 60 * S, 10 * S)
        return render

    A = np.array([13.0, 14.0, 15.0, 16.0])
    B = np.array([23.0, 24.0, 25.0, 26.0])
    C = np.array([8.0, 9.0, 10.0, 11.0])

    def _one(self, blk):
        assert blk.n_series == 1
        return blk.values[0]

    def test_combiners(self, teng):
        np.testing.assert_allclose(
            self._one(teng("averageSeries(t.*)")), (self.A + self.B + self.C) / 3)
        np.testing.assert_allclose(self._one(teng("maxSeries(t.*)")), self.B)
        np.testing.assert_allclose(self._one(teng("minSeries(t.*)")), self.C)
        np.testing.assert_allclose(
            self._one(teng("multiplySeries(t.*)")), self.A * self.B * self.C)
        np.testing.assert_allclose(
            self._one(teng("stddevSeries(t.*)")),
            np.std([self.A, self.B, self.C], axis=0))

    def test_pointwise(self, teng):
        np.testing.assert_allclose(
            self._one(teng("absolute(scale(t.a, -1))")), self.A)
        d = self._one(teng("derivative(t.a)"))
        assert np.isnan(d[0])
        np.testing.assert_allclose(d[1:], 1.0)
        nn = self._one(teng("nonNegativeDerivative(scale(t.a, -1))"))
        assert np.isnan(nn).all()  # strictly decreasing -> all masked
        cb = self._one(teng('consolidateBy(t.a, "max")'))
        np.testing.assert_allclose(cb, self.A)  # annotation only

    def test_time_slice_and_keep_last(self, teng):
        # graphite-web timeSlice is end-INCLUSIVE: the point at exactly
        # endSliceAt (T0+50, value 15) survives.
        t0s = (T0 + 30 * S) // S
        sliced = self._one(teng(f"timeSlice(t.a, {t0s}, {t0s + 20})"))
        np.testing.assert_allclose(sliced[:3], [13.0, 14.0, 15.0])
        assert np.isnan(sliced[3:]).all()
        kept = self._one(teng(f"keepLastValue(timeSlice(t.a, {t0s}, {t0s + 20}))"))
        np.testing.assert_allclose(kept, [13.0, 14.0, 15.0, 15.0])

    def test_filters_by_stat(self, teng):
        assert teng("averageAbove(t.*, 12)").n_series == 2     # a, b
        np.testing.assert_allclose(
            self._one(teng("averageBelow(t.*, 12)")), self.C)
        assert teng("minimumAbove(t.*, 10)").n_series == 2     # a, b
        np.testing.assert_allclose(
            self._one(teng("minimumBelow(t.*, 10)")), self.C)
        assert teng("maximumBelow(t.*, 20)").n_series == 2     # a, c
        np.testing.assert_allclose(
            self._one(teng("currentAbove(t.*, 20)")), self.B)

    def test_select_and_sort(self, teng):
        np.testing.assert_allclose(
            self._one(teng("highestCurrent(t.*, 1)")), self.B)
        np.testing.assert_allclose(
            self._one(teng("lowestAverage(t.*, 1)")), self.C)
        np.testing.assert_allclose(
            self._one(teng("highestMax(t.*, 1)")), self.B)
        srt = teng("sortByMaxima(t.*)")
        np.testing.assert_allclose(srt.values[0], self.B)
        np.testing.assert_allclose(srt.values[-1], self.C)
        assert teng("limit(t.*, 2)").n_series == 2

    def test_name_filters(self, teng):
        assert teng('exclude(t.*, "b")').n_series == 2
        np.testing.assert_allclose(self._one(teng('grep(t.*, "b")')), self.B)

    def test_percentile_filters(self, teng):
        # rank-based percentile (common/percentiles.go GetPercentile):
        # p50 of [13..16] -> rank ceil(0.5*4)=2 -> sorted[1] = 14.
        above = self._one(teng("removeAbovePercentile(t.a, 50)"))
        np.testing.assert_allclose(above[:2], [13.0, 14.0])
        assert np.isnan(above[2:]).all()
        # removeBelow keeps values >= the percentile: 14 survives.
        below = self._one(teng("removeBelowPercentile(t.a, 50)"))
        assert np.isnan(below[0])
        np.testing.assert_allclose(below[1:], [14.0, 15.0, 16.0])
        # means 14.5/24.5/9.5; rank-based p90=24.5, p10=9.5; the filter
        # keeps anything NOT strictly inside (lo, hi) -> b and c survive
        out = teng("averageOutsidePercentile(t.*, 90)")
        assert out.n_series == 2
        assert {v[0] for v in out.values} == {23.0, 8.0}

    def test_moving_and_summarize(self, teng):
        # moving* windows EXCLUDE the current point (the W points before
        # it): at T0+30 movingMax over scale(t.a,-1) sees -11, -12.
        np.testing.assert_allclose(
            self._one(teng("movingMax(scale(t.a, -1), 2)")),
            [-11.0, -12.0, -13.0, -14.0])
        np.testing.assert_allclose(
            self._one(teng("movingMin(t.a, 2)")), [11.0, 12.0, 13.0, 14.0])
        # stdev's window INCLUDES the current point (common/transform.go)
        # and is the POPULATION stddev: two consecutive ints -> 0.5.
        np.testing.assert_allclose(
            self._one(teng("stdev(t.a, 2)")), 0.5, rtol=1e-6)
        # summarize default aligns buckets to EPOCH multiples of the
        # interval (summarize.go): the grid starts at floor(T0+30, 20s) =
        # T0+20, so buckets hold {13}, {14,15}, {16}.
        summ = teng('summarize(t.a, "20s", "sum")')
        np.testing.assert_allclose(self._one(summ), [13.0, 29.0, 16.0])
        assert summ.meta.step_ns == 20 * S
        assert summ.meta.start_ns == T0 + 20 * S
        # alignToFrom=true counts buckets from the series start instead
        summ2 = teng('summarize(t.a, "20s", "sum", true)')
        np.testing.assert_allclose(self._one(summ2), [27.0, 31.0])
        # QUOTED "false" must mean false (Python truthiness would flip it)
        summ3 = teng('summarize(t.a, "20s", "sum", "false")')
        np.testing.assert_allclose(self._one(summ3), [13.0, 29.0, 16.0])
        # last: per-bucket final finite value
        summ4 = teng('summarize(t.a, "20s", "last")')
        np.testing.assert_allclose(self._one(summ4), [13.0, 15.0, 16.0])

    def test_summarize_aligned_fast_path(self, genv):
        # An epoch-aligned query window with uniform buckets takes the
        # reshape fast path; values must match the general path's
        # semantics: T0+40..T0+70 @10s = [14,15,16,17] -> 20s sums. The
        # block's exclusive end (T0+80) lands ON the interval grid, so
        # summarize.go's newEnd = floor(end, interval) + interval sizing
        # emits one trailing empty (NaN) bucket at T0+80.
        c, db, now = genv
        ingest_paths(c, now, [(b"t.a", 10.0)])
        eng = GraphiteEngine(c.engine.storage)
        blk = eng.render('summarize(t.a, "20s", "sum")',
                         T0 + 40 * S, T0 + 70 * S, 10 * S)
        np.testing.assert_allclose(blk.values[0], [29.0, 33.0, np.nan])
        assert blk.meta.start_ns == T0 + 40 * S
        assert blk.meta.steps == 3

    def test_wildcards_grouping(self, teng):
        blk = teng("averageSeriesWithWildcards(t.*, 1)")
        np.testing.assert_allclose(
            self._one(blk), (self.A + self.B + self.C) / 3)
