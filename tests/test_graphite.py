"""Carbon ingestion + graphite query language tests (reference:
src/metrics/carbon/parser.go, src/query/graphite/native builtins, the
carbon docker integration test flow: line in -> render out)."""

import json
import socket
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from m3_tpu.cluster import kv as cluster_kv
from m3_tpu.coordinator import run_embedded
from m3_tpu.coordinator.carbon_ingest import CarbonServer
from m3_tpu.index.namespace_index import NamespaceIndex
from m3_tpu.metrics import carbon
from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.query.graphite import (
    GraphiteEngine,
    parse_target,
    path_to_matchers,
    series_name,
)
from m3_tpu.storage.database import Database
from m3_tpu.storage.namespace import NamespaceOptions

S = 1_000_000_000
T0 = 1_600_000_000 * S


class TestCarbonParser:
    def test_parse_valid(self):
        assert carbon.parse_line(b"servers.web01.cpu 42.5 1600000000") == (
            b"servers.web01.cpu", 42.5, 1600000000)

    def test_parse_rejects_malformed(self):
        for bad in [b"", b"onlypath", b"a.b 1.0", b"a.b x 123",
                    b".lead 1 2", b"trail. 1 2", b"a.b nan 123"]:
            assert carbon.parse_line(bad) is None

    def test_path_tags_roundtrip(self):
        tags = carbon.path_to_tags(b"a.b.c")
        assert tags == {b"__g0__": b"a", b"__g1__": b"b", b"__g2__": b"c"}
        assert carbon.tags_to_path(tags) == b"a.b.c"


class TestPathMatchers:
    def test_literal_and_glob(self):
        ms = path_to_matchers("servers.*.cpu")
        assert ms[0].value == b"servers"
        assert ms[1].type.name == "REGEXP"
        # depth guard: no __g3__ allowed
        assert ms[-1].name == b"__g3__"

    def test_alternation(self):
        ms = path_to_matchers("servers.{web01,web02}.cpu")
        assert ms[1].matches(b"web01") and ms[1].matches(b"web02")
        assert not ms[1].matches(b"web03")


class TestTargetParser:
    def test_nested_calls(self):
        ast = parse_target('scale(sumSeries(servers.*.cpu), 0.5)')
        assert ast.func == "scale"
        assert ast.args[0].func == "sumSeries"
        assert ast.args[1].value == 0.5


@pytest.fixture
def genv():
    now = {"t": T0}
    db = Database(ShardSet(8), clock=lambda: now["t"])
    db.create_namespace(b"default", NamespaceOptions(),
                        index=NamespaceIndex(clock=lambda: now["t"]))
    c = run_embedded(db, clock=lambda: now["t"])
    yield c, db, now
    c.close()


def ingest_paths(c, now, paths_values):
    for i in range(12):
        now["t"] = T0 + i * 10 * S
        for path, base in paths_values:
            tags = carbon.path_to_tags(path)
            c.writer.write(tags, T0 + i * 10 * S, base + i)


class TestGraphiteEngine:
    def test_glob_fetch_and_sum(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"servers.web01.cpu", 10.0),
                              (b"servers.web02.cpu", 20.0),
                              (b"servers.web01.mem", 99.0)])
        eng = GraphiteEngine(c.engine.storage)
        blk = eng.render("servers.*.cpu", T0 + 30 * S, T0 + 110 * S, 10 * S)
        assert blk.n_series == 2
        blk = eng.render("sumSeries(servers.*.cpu)", T0 + 30 * S, T0 + 110 * S,
                         10 * S)
        assert blk.n_series == 1
        np.testing.assert_allclose(blk.values[0][0], 10 + 20 + 2 * 3)

    def test_alias_by_node_and_scale(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"servers.web01.cpu", 10.0)])
        eng = GraphiteEngine(c.engine.storage)
        blk = eng.render("aliasByNode(scale(servers.web01.cpu, 2), 1)",
                         T0 + 30 * S, T0 + 60 * S, 10 * S)
        assert series_name(blk.series_tags[0]) == b"web01"
        np.testing.assert_allclose(blk.values[0][0], 2 * 13.0)

    def test_group_by_node(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"dc1.web01.cpu", 1.0), (b"dc1.web02.cpu", 2.0),
                              (b"dc2.web03.cpu", 5.0)])
        eng = GraphiteEngine(c.engine.storage)
        blk = eng.render('groupByNode(*.*.cpu, 0, "sum")',
                         T0 + 30 * S, T0 + 30 * S, 10 * S)
        got = {series_name(t): v[0] for t, v in zip(blk.series_tags, blk.values)}
        assert got[b"dc1"] == (1 + 3) + (2 + 3)
        assert got[b"dc2"] == 5 + 3

    def test_per_second_and_moving_average(self, genv):
        c, db, now = genv
        ingest_paths(c, now, [(b"counters.reqs", 0.0)])
        eng = GraphiteEngine(c.engine.storage)
        blk = eng.render("perSecond(counters.reqs)", T0 + 30 * S, T0 + 80 * S,
                         10 * S)
        np.testing.assert_allclose(blk.values[0][1:], 0.1)  # +1 per 10s
        blk = eng.render("movingAverage(counters.reqs, 3)", T0 + 30 * S,
                         T0 + 80 * S, 10 * S)
        np.testing.assert_allclose(blk.values[0][0], (1 + 2 + 3) / 3)


class TestCarbonServerEndToEnd:
    def test_tcp_lines_to_graphite_render(self, genv):
        c, db, now = genv
        srv = CarbonServer(c.writer).start()
        try:
            host, _, port = srv.endpoint.rpartition(":")
            lines = []
            for i in range(6):
                lines.append(b"foo.bar.baz %f %d" % (float(i), (T0 + i * 10 * S) // S))
            now["t"] = T0 + 60 * S
            with socket.create_connection((host, int(port))) as sock:
                sock.sendall(b"\n".join(lines) + b"\nbad line\n")
            deadline = time.time() + 5
            while srv.lines_ingested < 6 and time.time() < deadline:
                time.sleep(0.02)
            assert srv.lines_ingested == 6
            assert srv.lines_malformed == 1
            # Render through the HTTP API.
            q = urllib.parse.urlencode(
                {"target": "foo.bar.baz", "from": T0 / S, "until": T0 / S + 50,
                 "step": "10"})
            with urllib.request.urlopen(
                    f"{c.endpoint}/api/v1/graphite/render?{q}") as resp:
                out = json.loads(resp.read())
            assert out[0]["target"] == "foo.bar.baz"
            vals = [v for v, _ in out[0]["datapoints"] if v is not None]
            assert vals == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
        finally:
            srv.close()
