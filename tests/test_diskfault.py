"""Disk-fault plane: seeded I/O fault injection (testing/faultfs),
serve-time row-checksum verification + quarantine, background scrubbing
with repair routing, and full-disk graceful degradation (reference
model: dbnode digest verification at fileset open, repair.go's
background sweeps, and the dtest destructive disk scenarios).

The DiskFaultScenario composition drill at the bottom runs the whole
stack at once: RF=3, one node's storage under a seeded fault plan,
zero acked-write loss / zero fabrication asserted end-state."""

import errno
import json
import os

import numpy as np
import pytest

from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.persist import commitlog as cl
from m3_tpu.persist import fs as pfs
from m3_tpu.persist.diskio import (CorruptionError, DiskFullError,
                                   DiskWriteError, classify_write_error)
from m3_tpu.storage.block import encode_block
from m3_tpu.storage.database import Database
from m3_tpu.storage.namespace import NamespaceOptions
from m3_tpu.storage.retriever import BlockRetriever
from m3_tpu.storage.scrub import DatabaseScrubber, ScrubOptions
from m3_tpu.storage.series import SeriesRegistry
from m3_tpu.storage.timerange import overlaps
from m3_tpu.testing import faultfs
from m3_tpu.testing.scenario import (DiskFaultScenario,
                                     DiskFaultScenarioOptions)
from m3_tpu.utils import xtime
from m3_tpu.utils.health import DiskHealth, Priority
from m3_tpu.utils.limits import Backpressure

NS = b"default"
BLOCK = 2 * xtime.HOUR
T0 = 1_600_000_000 * xtime.SECOND - (1_600_000_000 * xtime.SECOND) % BLOCK


@pytest.fixture(autouse=True)
def _clean_seam():
    """Every test starts and ends on the real disk seam."""
    faultfs.uninstall()
    yield
    faultfs.uninstall()


def _mk_fileset(root, rng, n=8, w=6, shard=1, block_start=T0):
    reg = SeriesRegistry()
    ids = [b"df.%d" % i for i in range(n)]
    for sid in ids:
        reg.get_or_create(sid)
    ts = (block_start + np.arange(w, dtype=np.int64)[None, :] * 10
          * xtime.SECOND + np.zeros((n, 1), np.int64))
    vals = rng.integers(0, 50, size=(n, w)).astype(np.float64)
    blk = encode_block(block_start, np.arange(n, dtype=np.int32), ts, vals,
                       np.full(n, w, np.int32))
    pm = pfs.PersistManager(root)
    return pm, ids, pm.write_block(NS, shard, blk, reg)


# ---------------------------------------------------------------------------
# faultfs: the schedule is a pure function of the seed
# ---------------------------------------------------------------------------


class TestFaultfsDeterminism:
    def test_decisions_replay_schedule_exactly(self, tmp_path):
        plan = faultfs.DiskFaultPlan(seed=3, read_flip=0.4, read_short=0.2)
        p = os.path.join(str(tmp_path), "dir", "blob.bin")
        os.makedirs(os.path.dirname(p))
        with open(p, "wb") as f:
            f.write(b"x" * 64)
        io = faultfs.FaultIO(plan)
        for _ in range(9):
            with io.open(p, "rb") as f:
                f.read()
        key = faultfs._path_key(p)
        assert io.decisions[("read", key)] == plan.schedule("read", key, 9)
        # And a second injector replays the identical stream.
        io2 = faultfs.FaultIO(plan)
        for _ in range(9):
            with io2.open(p, "rb") as f:
                f.read()
        assert io2.decisions == io.decisions

    def test_schedule_independent_per_op_and_key(self):
        plan = faultfs.DiskFaultPlan(seed=5, read_flip=0.5, write_eio=0.5)
        a = plan.schedule("read", "d/a.bin", 32)
        assert plan.schedule("read", "d/a.bin", 32) == a  # pure
        assert plan.schedule("read", "d/b.bin", 32) != a  # per-key stream
        assert plan.schedule("write", "d/a.bin", 32) != a  # per-op stream

    def test_path_filter_scopes_faults(self, tmp_path):
        inside = os.path.join(str(tmp_path), "node0", "f.bin")
        outside = os.path.join(str(tmp_path), "node1", "f.bin")
        for p in (inside, outside):
            os.makedirs(os.path.dirname(p))
            with open(p, "wb") as f:
                f.write(b"y" * 32)
        plan = faultfs.DiskFaultPlan(
            seed=1, read_flip=1.0,
            path_filter=os.path.join(str(tmp_path), "node0") + os.sep)
        io = faultfs.FaultIO(plan)
        with io.open(outside, "rb") as f:
            assert f.read() == b"y" * 32  # untouched, no decision drawn
        with io.open(inside, "rb") as f:
            assert f.read() != b"y" * 32  # exactly one bit flipped
        assert io.faults_injected == 1

    def test_flip_changes_one_bit_short_truncates(self, tmp_path):
        p = os.path.join(str(tmp_path), "d", "f.bin")
        os.makedirs(os.path.dirname(p))
        data = bytes(range(64))
        with open(p, "wb") as f:
            f.write(data)
        io = faultfs.FaultIO(faultfs.DiskFaultPlan(seed=2, read_flip=1.0))
        with io.open(p, "rb") as f:
            got = f.read()
        diff = [i for i in range(64) if got[i] != data[i]]
        assert len(diff) == 1
        assert bin(got[diff[0]] ^ data[diff[0]]).count("1") == 1
        io = faultfs.FaultIO(faultfs.DiskFaultPlan(seed=2, read_short=1.0))
        with io.open(p, "rb") as f:
            assert len(f.read()) < len(data)

    def test_write_faults_raise_before_bytes_land(self, tmp_path):
        p = os.path.join(str(tmp_path), "d", "w.bin")
        os.makedirs(os.path.dirname(p))
        io = faultfs.FaultIO(faultfs.DiskFaultPlan(seed=2, write_eio=1.0))
        with pytest.raises(OSError) as ei:
            with io.open(p, "wb") as f:
                f.write(b"data")
        assert ei.value.errno == errno.EIO
        assert os.path.getsize(p) == 0  # nothing landed
        io = faultfs.FaultIO(faultfs.DiskFaultPlan(seed=2, write_enospc=1.0))
        with pytest.raises(OSError) as ei:
            with io.open(p, "wb") as f:
                f.write(b"data")
        assert ei.value.errno == errno.ENOSPC
        assert isinstance(classify_write_error(ei.value, p), DiskFullError)

    def test_fsync_lie_then_power_cut_drops_tail(self, tmp_path):
        p = os.path.join(str(tmp_path), "d", "wal.bin")
        os.makedirs(os.path.dirname(p))
        io = faultfs.FaultIO(faultfs.DiskFaultPlan(seed=2, fsync_lie=1.0))
        f = io.open(p, "wb")
        f.write(b"acked-but-never-synced")
        io.fsync(f)  # lies: acks without syncing
        f.close()
        assert io.fsync_lies == 1
        assert io.power_cut() == 1
        assert os.path.getsize(p) == 0  # the lie cost the whole tail

    def test_torn_replace_leaves_incomplete_fileset(self, tmp_path, rng):
        root = str(tmp_path)
        faultfs.install(faultfs.DiskFaultPlan(seed=4, torn_replace=1.0))
        with pytest.raises(DiskWriteError):
            _mk_fileset(root, rng)
        faultfs.uninstall()
        # The torn destination exists but must never be servable.
        shard_dir = os.path.join(root, NS.decode(), "shard-00001")
        torn = [d for d in os.listdir(shard_dir)
                if d.startswith("fileset-") and not d.endswith(".tmp")]
        assert torn
        assert not pfs.fileset_complete(os.path.join(shard_dir, torn[0]))
        assert pfs.PersistManager(root).list_filesets(NS, 1) == []

    def test_memmap_fault_materializes_flipped_copy(self, tmp_path, rng):
        root = str(tmp_path)
        _pm, _ids, path = _mk_fileset(root, rng)
        clean = pfs.FilesetReader(path, verify=True)
        clean_words = np.asarray(clean._words).copy()
        faultfs.install(faultfs.DiskFaultPlan(seed=6, read_flip=1.0))
        # Every component read is now rotten; some typed layer — the
        # checkpoint completeness probe, the digest chain, or the
        # per-row adlers — must catch it. Never a clean read.
        with pytest.raises((CorruptionError, FileNotFoundError)):
            r = pfs.FilesetReader(path, verify=False)
            if np.array_equal(np.asarray(r._words), clean_words):
                raise AssertionError("memmap fault did not corrupt a copy")
            r.verify_rows()
        faultfs.uninstall()
        # The file on disk itself is untouched: faults live in the seam.
        np.testing.assert_array_equal(
            np.asarray(pfs.FilesetReader(path, verify=True)._words),
            clean_words)


# ---------------------------------------------------------------------------
# serve-time verification + quarantine
# ---------------------------------------------------------------------------


def _flip_data_byte(path, offset=3):
    dpath = os.path.join(path, pfs.DATA_FILE)
    with open(dpath, "rb+") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0x10]))


class TestServeTimeVerify:
    def test_lazy_verify_catches_rot_and_names_rows(self, tmp_path, rng):
        root = str(tmp_path)
        _pm, ids, path = _mk_fileset(root, rng)
        _flip_data_byte(path)  # lands in row 0's codewords
        blk, _ = pfs.FilesetReader(path, verify=False).to_block()
        with pytest.raises(CorruptionError) as ei:
            blk.read_all()
        assert ei.value.rows == [0]
        assert ei.value.ids == [ids[0]]
        assert ei.value.path == path
        with pytest.raises(CorruptionError):
            blk.read(0)  # per-row read path verifies too

    def test_verified_once_per_generation(self, tmp_path, rng):
        root = str(tmp_path)
        _pm, _ids, path = _mk_fileset(root, rng)
        blk, _ = pfs.FilesetReader(path, verify=False).to_block()
        assert blk.expected_row_sums is not None
        blk.read_all()
        assert blk._rows_verified is True
        # Cached: tampering the expectation after the first read is not
        # re-checked — verification is once per loaded generation.
        blk.expected_row_sums = blk.expected_row_sums + 1
        blk.read_all()

    def test_seeker_detects_flipped_row(self, tmp_path, rng):
        root = str(tmp_path)
        _pm, ids, path = _mk_fileset(root, rng)
        _flip_data_byte(path)
        with pytest.raises(CorruptionError):
            # Digest-at-open (index/bloom) or row-adler-at-seek (data):
            # one of the typed layers must refuse the rotten bytes.
            sk = pfs.Seeker(path)
            sk.seek(ids[0])

    def test_retriever_quarantines_and_serves_none(self, tmp_path, rng):
        root = str(tmp_path)
        pm, ids, path = _mk_fileset(root, rng)
        _flip_data_byte(path)
        r = BlockRetriever(pm)
        assert r.retrieve(NS, 1, T0, ids[0]) is None  # detected, not served
        # The fileset moved to quarantine with a JSON sidecar naming it.
        q = pm.list_quarantined(NS, 1)
        assert [bs for bs, _p in q] == [T0]
        sidecar = json.load(open(q[0][1] + ".json"))
        assert "reason" in sidecar and sidecar["rows"]
        # Gone from the serving listing; clear_quarantined removes it.
        assert pm.list_filesets(NS, 1) == []
        assert r.block_starts(NS, 1) == {}
        assert pm.clear_quarantined(NS, 1, T0) is True
        assert pm.list_quarantined(NS, 1) == []

    def test_clean_fileset_serves_through_retriever(self, tmp_path, rng):
        root = str(tmp_path)
        pm, ids, _path = _mk_fileset(root, rng)
        r = BlockRetriever(pm)
        got = r.retrieve(NS, 1, T0, ids[2])
        assert got is not None and len(got[0]) == 6
        assert pm.list_quarantined(NS, 1) == []


# ---------------------------------------------------------------------------
# DiskHealth + read-only degradation
# ---------------------------------------------------------------------------


class TestDiskHealth:
    def test_trips_after_consecutive_failures_and_recovers(self):
        h = DiskHealth(trip_after=3)
        assert not h.read_only()
        h.failure()
        h.failure()
        assert not h.read_only()
        assert h.saturation() == pytest.approx(2 / 3)
        h.failure()
        assert h.read_only()
        assert h.saturation() == 1.0
        h.success()  # one durable success clears the posture
        assert not h.read_only()
        assert h.failures == 3 and h.trips == 1

    def test_database_sheds_normal_keeps_critical(self, tmp_path, rng):
        now = {"t": T0 + xtime.MINUTE}
        db = Database(ShardSet(8), clock=lambda: now["t"])
        db.create_namespace(NS, NamespaceOptions(index_enabled=False))
        pm = pfs.PersistManager(os.path.join(str(tmp_path), "data"))
        ids = [b"deg-%d" % i for i in range(32)]
        db.write_batch(NS, ids,
                       np.full(32, T0 + 30 * xtime.SECOND, np.int64),
                       rng.standard_normal(32))
        now["t"] = T0 + BLOCK + 11 * xtime.MINUTE
        db.tick()
        faultfs.install(faultfs.DiskFaultPlan(seed=9, write_enospc=1.0))
        assert db.flush(pm) == 0  # every block's flush ENOSPCed, typed
        assert db.disk_health.read_only()
        with pytest.raises(Backpressure):
            db.write(NS, b"deg-0", now["t"], 1.0)
        db.write(NS, b"deg-0", now["t"], 2.0,
                 priority=Priority.CRITICAL)  # never shed
        t, v = db.read(NS, b"deg-0", 0, now["t"] + 1)  # reads flow
        assert 2.0 in v.tolist()
        faultfs.uninstall()
        assert db.flush(pm) > 0  # FAILED blocks stayed on the schedule
        assert not db.disk_health.read_only()  # auto-recovery
        db.write(NS, b"deg-0", now["t"], 3.0)  # NORMAL flows again

    def test_wal_append_failure_is_typed_ack_failure(self, tmp_path):
        faultfs.install(faultfs.DiskFaultPlan(seed=9, write_eio=1.0))
        log = cl.CommitLog(os.path.join(str(tmp_path), "cl"),
                           strategy=cl.Strategy.WRITE_WAIT)
        db = Database(ShardSet(2), commitlog=log, clock=lambda: T0 + 1)
        db.create_namespace(NS, NamespaceOptions(index_enabled=False))
        with pytest.raises(DiskWriteError):
            db.write(NS, b"wal-0", T0, 1.0)
        assert db.disk_health.failures >= 1


# ---------------------------------------------------------------------------
# DatabaseScrubber: detect -> quarantine -> repair -> un-quarantine
# ---------------------------------------------------------------------------


def _scrub_db(tmp_path, rng):
    """A db whose shard 1 holds a sealed, flushed, cold block — with
    the sealed copy still RESIDENT (the no-peer repair source)."""
    now = {"t": T0 + 5 * xtime.MINUTE}
    db = Database(ShardSet(2), clock=lambda: now["t"])
    db.create_namespace(NS, NamespaceOptions(index_enabled=False))
    pm = pfs.PersistManager(os.path.join(str(tmp_path), "data"))
    db.set_retriever(BlockRetriever(pm))
    ids = [b"scrub-%d" % i for i in range(8)]
    shard_ids = [sid for sid in ids if db.shard_set.lookup(sid) == 1]
    assert shard_ids  # murmur spreads 8 ids over 2 shards
    db.write_batch(NS, ids, np.full(8, T0 + 4 * xtime.MINUTE, np.int64),
                   rng.standard_normal(8))
    now["t"] = T0 + 3 * BLOCK  # cold: outside the 2-block mutable head
    db.tick()
    assert db.flush(pm) >= 1
    return db, pm, now, shard_ids


class TestDatabaseScrubber:
    def test_sweep_detects_quarantines_repairs_unquarantines(
            self, tmp_path, rng):
        db, pm, now, shard_ids = _scrub_db(tmp_path, rng)
        path = dict(pm.list_filesets(NS, 1))[T0]
        _flip_data_byte(path)
        scrubber = DatabaseScrubber(db, pm, opts=ScrubOptions(seed=1))
        st = scrubber.run(now_ns=now["t"])[NS]
        assert st.filesets_scanned >= 1 and st.corrupt_found == 1
        assert st.quarantined == 1
        # No repairer: the RESIDENT sealed block is the repair source —
        # its flush state cleared, the quarantined copy removed.
        assert st.unquarantined == 1
        assert pm.list_quarantined(NS, 1) == []
        # The next flush sweep rewrites the fileset, clean.
        assert db.flush(pm) >= 1
        path2 = dict(pm.list_filesets(NS, 1))[T0]
        pfs.FilesetReader(path2, verify=True).verify_rows()
        # ... and the data still serves.
        t, v = db.read(NS, shard_ids[0], 0, now["t"])
        assert len(t) == 1

    def test_clean_sweep_touches_nothing(self, tmp_path, rng):
        db, pm, now, _ = _scrub_db(tmp_path, rng)
        st = DatabaseScrubber(db, pm, opts=ScrubOptions(seed=1)).run(
            now_ns=now["t"])[NS]
        assert st.corrupt_found == 0 and st.quarantined == 0
        assert st.filesets_scanned >= 1 and st.bytes_verified > 0

    def test_warm_head_not_scanned(self, tmp_path, rng):
        """Blocks inside the two-block mutable head are skipped: a flush
        may still be racing to write them."""
        db, pm, now, _ = _scrub_db(tmp_path, rng)
        now["t"] = T0 + BLOCK + 11 * xtime.MINUTE  # head is warm again
        st = DatabaseScrubber(db, pm, opts=ScrubOptions(seed=1)).run(
            now_ns=now["t"])[NS]
        assert st.filesets_scanned == 0

    def test_quarantined_past_retention_cleared_without_repair(
            self, tmp_path, rng):
        db, pm, now, _ = _scrub_db(tmp_path, rng)
        path = dict(pm.list_filesets(NS, 1))[T0]
        assert pfs.quarantine_fileset(path, reason="test") is not None
        retention = db.namespace(NS).opts.retention_ns
        now["t"] = T0 + BLOCK + retention + xtime.MINUTE
        st = DatabaseScrubber(db, pm, opts=ScrubOptions(seed=1)).run(
            now_ns=now["t"])[NS]
        assert st.unquarantined == 1 and st.repair_attempts == 0
        assert pm.list_quarantined(NS, 1) == []

    def test_seeded_jitter_deterministic_and_backoff_grows(self):
        db = Database(ShardSet(1), clock=lambda: T0)
        a = DatabaseScrubber(db, None, opts=ScrubOptions(seed=5))
        b = DatabaseScrubber(db, None, opts=ScrubOptions(seed=5))
        assert [a.next_delay_s() for _ in range(4)] \
            == [b.next_delay_s() for _ in range(4)]
        a.consecutive_failures = 3
        assert a.next_delay_s() > b.next_delay_s()


# ---------------------------------------------------------------------------
# bootstrap: corrupt filesets quarantined, range falls through the chain
# ---------------------------------------------------------------------------


class TestBootstrapQuarantine:
    def test_corrupt_fileset_quarantined_not_claimed(self, tmp_path, rng):
        from m3_tpu.storage.bootstrap import (BootstrapContext,
                                              BootstrapProcess)

        root = str(tmp_path)
        pm, ids, path = _mk_fileset(root, rng)
        _flip_data_byte(path)
        db = Database(ShardSet(2), clock=lambda: T0 + BLOCK)
        db.create_namespace(NS, NamespaceOptions(index_enabled=False))
        proc = BootstrapProcess(chain=("filesystem",),
                                ctx=BootstrapContext(persist=pm))
        res = proc.run(db)[NS]
        # Not served, not silently skipped: quarantined + surfaced.
        assert pm.list_quarantined(NS, 1) and pm.list_filesets(NS, 1) == []
        assert any("quarantined" in n for n in res.notes)
        # The range stays UNCLAIMED so the chain's next source owns it.
        assert not overlaps(res.claimed["filesystem"].ranges(1), T0, T0 + BLOCK)
        assert overlaps(res.unfulfilled.ranges(1), T0, T0 + BLOCK)
        t, _v = db.read(NS, ids[0], 0, T0 + BLOCK)
        assert len(t) == 0

    def test_clean_fileset_claims_and_serves(self, tmp_path, rng):
        from m3_tpu.storage.bootstrap import (BootstrapContext,
                                              BootstrapProcess)

        root = str(tmp_path)
        pm, ids, _path = _mk_fileset(root, rng)
        db = Database(ShardSet(2), clock=lambda: T0 + BLOCK)
        db.create_namespace(NS, NamespaceOptions(index_enabled=False))
        res = BootstrapProcess(chain=("filesystem",),
                               ctx=BootstrapContext(persist=pm)).run(db)[NS]
        assert res.notes == []
        assert overlaps(res.claimed["filesystem"].ranges(1), T0, T0 + BLOCK)
        sid = next(s for s in ids if db.shard_set.lookup(s) == 1)
        t, _v = db.read(NS, sid, 0, T0 + BLOCK)
        assert len(t) == 6


# ---------------------------------------------------------------------------
# the composition drill: everything at once, zero loss / zero fabrication
# ---------------------------------------------------------------------------


def _drill(seed):
    sc = DiskFaultScenario(DiskFaultScenarioOptions(seed=seed))
    try:
        return sc.verify(sc.run())
    finally:
        sc.close()


class TestDiskFaultScenario:
    @pytest.mark.parametrize("seed", [7, 11])
    def test_zero_loss_zero_fabrication(self, seed):
        res = _drill(seed)
        assert res.verified_points > 0
        assert res.quarantined_after_faults >= 1
        assert res.scrub_stats.blocks_repaired >= 1
        assert res.health_tripped and res.recovered

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [23, 42, 1234])
    def test_more_seeds(self, seed):
        res = _drill(seed)
        assert res.verified_points > 0
