"""Dynamic namespace registry (reference:
src/dbnode/storage/namespace_watch.go): a namespace added to the KV
registry is created on watching databases and serves without restart;
removals drop it; the watch seeds an absent registry from config-defined
namespaces so KV becomes authoritative."""

import json
import time

import numpy as np
import pytest

from m3_tpu.cluster import kv as cluster_kv
from m3_tpu.cluster.kv_service import KVServer, RemoteStore
from m3_tpu.index.namespace_index import NamespaceIndex
from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.storage.database import Database
from m3_tpu.storage.namespace import NamespaceOptions
from m3_tpu.storage.namespace_watch import REGISTRY_KEY, NamespaceWatch

S = 1_000_000_000
T0 = 1_700_000_000 * S
HOUR = 3600 * S


def _await(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def make_db():
    db = Database(ShardSet(4), clock=lambda: T0)
    db.create_namespace(b"default", NamespaceOptions(),
                        index=NamespaceIndex(clock=lambda: T0))
    return db


class TestLocalRegistry:
    def test_seed_then_add_and_remove(self):
        db = make_db()
        store = cluster_kv.MemStore()
        watch = NamespaceWatch(db, store).start()
        # Seeded from the live namespace set.
        reg = json.loads(store.get(REGISTRY_KEY).data)
        assert set(reg) == {"default"}
        # Registry write from "elsewhere" creates the namespace live.
        reg["metrics_1m"] = {"retention_ns": 40 * 24 * HOUR,
                             "block_size_ns": 4 * HOUR, "index_enabled": True}
        store.set(REGISTRY_KEY, json.dumps(reg).encode())
        assert b"metrics_1m" in db.namespaces
        ns = db.namespace(b"metrics_1m")
        assert ns.opts.retention_ns == 40 * 24 * HOUR
        assert ns.opts.block_size_ns == 4 * HOUR
        assert ns.index is not None
        # ... and serves immediately.
        db.write(b"metrics_1m", b"series", T0, 1.5, tags={b"a": b"b"})
        t, v = db.read(b"metrics_1m", b"series", 0, 2**62)
        assert v.tolist() == [1.5]
        # Removal drops it.
        del reg["metrics_1m"]
        store.set(REGISTRY_KEY, json.dumps(reg).encode())
        assert b"metrics_1m" not in db.namespaces
        assert b"default" in db.namespaces

    def test_add_helper_creates_and_publishes(self):
        db = make_db()
        store = cluster_kv.MemStore()
        watch = NamespaceWatch(db, store).start()
        watch.add(b"agg_10s", retention_ns=2 * 24 * HOUR, index_enabled=False)
        assert b"agg_10s" in db.namespaces
        assert db.namespace(b"agg_10s").index is None
        reg = json.loads(store.get(REGISTRY_KEY).data)
        assert reg["agg_10s"]["index_enabled"] is False
        watch.remove(b"agg_10s")
        assert b"agg_10s" not in db.namespaces

    def test_no_index_when_disabled(self):
        db = make_db()
        store = cluster_kv.MemStore()
        NamespaceWatch(db, store).start()
        reg = json.loads(store.get(REGISTRY_KEY).data)
        reg["raw"] = {"retention_ns": HOUR, "index_enabled": False}
        store.set(REGISTRY_KEY, json.dumps(reg).encode())
        assert db.namespace(b"raw").index is None


class TestCrossProcess:
    def test_namespace_add_propagates_over_kv_service(self):
        """Two databases watching one KV process: an admin add on one node
        appears on the other via watch push, no restart."""
        srv = KVServer().start()
        try:
            db_a, db_b = make_db(), make_db()
            watch_a = NamespaceWatch(db_a, RemoteStore(srv.endpoint)).start()
            watch_b = NamespaceWatch(db_b, RemoteStore(srv.endpoint)).start()
            watch_a.add(b"new_ns", retention_ns=2 * HOUR)
            assert b"new_ns" in db_a.namespaces  # immediate locally
            assert _await(lambda: b"new_ns" in db_b.namespaces)
            db_b.write(b"new_ns", b"s", T0, 7.0)
            assert db_b.read(b"new_ns", b"s", 0, 2**62)[1].tolist() == [7.0]
            watch_b.remove(b"new_ns")
            assert _await(lambda: b"new_ns" not in db_a.namespaces)
        finally:
            srv.close()


class TestRegistryEvolution:
    def test_config_namespace_merged_into_existing_registry(self):
        """Restarting with a new config-defined namespace registers it in a
        pre-existing registry instead of the watch dropping it."""
        store = cluster_kv.MemStore()
        db1 = make_db()
        NamespaceWatch(db1, store).start()
        # "Restart" with an extra config namespace.
        db2 = make_db()
        db2.create_namespace(b"from_config", NamespaceOptions(
            retention_ns=6 * HOUR))
        NamespaceWatch(db2, store).start()
        assert b"from_config" in db2.namespaces  # not dropped
        reg = json.loads(store.get(REGISTRY_KEY).data)
        assert "from_config" in reg  # registered for peers
        assert _await(lambda: b"from_config" in db1.namespaces)

    def test_retention_update_applies_live(self):
        db = make_db()
        store = cluster_kv.MemStore()
        NamespaceWatch(db, store).start()
        reg = json.loads(store.get(REGISTRY_KEY).data)
        reg["default"]["retention_ns"] = 99 * HOUR
        store.set(REGISTRY_KEY, json.dumps(reg).encode())
        ns = db.namespace(b"default")
        assert ns.opts.retention_ns == 99 * HOUR
        assert all(sh.opts.retention_ns == 99 * HOUR
                   for sh in ns.shards.values())

    def test_idempotent_readd_of_existing_namespace(self):
        """Quickstart database_create against a config namespace must
        no-op, not 500 (same retention adopts live options)."""
        db = make_db()
        store = cluster_kv.MemStore()
        watch = NamespaceWatch(db, store).start()
        opts = db.namespace(b"default").opts
        watch.add(b"default", retention_ns=opts.retention_ns)  # no raise
        with pytest.raises(ValueError):
            watch.add(b"default", retention_ns=opts.retention_ns + HOUR)

    def test_stop_deregisters_callback(self):
        db = make_db()
        store = cluster_kv.MemStore()
        watch = NamespaceWatch(db, store).start()
        watch.stop()
        assert not store._callbacks.get(REGISTRY_KEY)
        reg = {"phantom": {"retention_ns": HOUR, "index_enabled": False}}
        store.set(REGISTRY_KEY, json.dumps(reg).encode())
        assert b"phantom" not in db.namespaces
        assert b"default" in db.namespaces
