"""Runtime race witness (utils/racewatch.py) + the
scripts/race_check.py gate: access-profile recording, racy-pair
computation, first-write (construction) skip, slots wrapping, dump
round-trips, ledger blessing, protection-model cross-checks, and the
gate's vacuous-pass refusal."""

import json
import pathlib
import subprocess
import sys
import threading

import pytest

from m3_tpu.analysis import race_rules
from m3_tpu.utils import racewatch

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture
def witness(monkeypatch):
    """Arm recording WITHOUT installing lockdep: held-lock snapshots
    come from a per-thread override so tests control the lock story
    exactly (lockdep only wraps in-repo-allocated locks, so test-local
    locks would read as held-nothing anyway)."""
    held = threading.local()
    monkeypatch.setattr(racewatch, "_held_locks",
                        lambda: frozenset(getattr(held, "locks", ())))
    monkeypatch.setattr(racewatch, "_INSTALLED", True)
    # fresh ident table: each test's throwaway class gets its own
    # descriptor even when names (Box.v) repeat across tests
    monkeypatch.setattr(racewatch, "_WATCHED", {})
    racewatch.reset()
    yield held
    racewatch.reset()


def in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


class TestProfileRecording:
    def test_disjoint_lock_cross_thread_write_is_racy(self, witness):
        class Box:
            def __init__(self):
                self.v = 0

        racewatch.watch(Box, "v")
        b = Box()
        witness.locks = ("Box._lock",)
        b.v = 1  # main thread, under the lock

        def other():
            witness.locks = ()
            b.v = 2  # lock-free from another thread

        in_thread(other)
        (f,) = racewatch.findings()
        assert f["attr"] == "Box.v"
        assert f["threads"] == 2
        assert f["racy"], f
        (a, c) = f["racy"][0]
        assert not (set(a["locks"]) & set(c["locks"]))

    def test_common_lock_pair_is_not_racy(self, witness):
        class Box:
            def __init__(self):
                self.v = 0

        racewatch.watch(Box, "v")
        b = Box()
        witness.locks = ("Box._lock",)
        b.v = 1

        def other():
            witness.locks = ("Box._lock",)
            b.v = 2

        in_thread(other)
        (f,) = racewatch.findings()
        assert f["threads"] == 2
        assert f["racy"] == []

    def test_read_read_pair_is_not_racy(self, witness):
        class Box:
            def __init__(self):
                self.v = 0

        racewatch.watch(Box, "v")
        b = Box()
        witness.locks = ()
        assert b.v == 0

        def other():
            witness.locks = ()
            assert b.v == 0

        in_thread(other)
        (f,) = racewatch.findings()
        assert f["threads"] == 2
        assert f["racy"] == []

    def test_first_write_is_construction_not_a_profile(self, witness):
        class Box:
            def __init__(self):
                self.v = 0

        racewatch.watch(Box, "v")
        witness.locks = ()
        Box()  # only the __init__ store: pre-publication by contract
        assert racewatch.observed_count() == 0
        b = Box()
        b.v = 1  # the SECOND store is a real write profile
        (f,) = racewatch.findings()
        assert [p["write"] for p in f["profiles"]] == [True]

    def test_profiles_deduplicate(self, witness):
        class Box:
            def __init__(self):
                self.v = 0

        racewatch.watch(Box, "v")
        b = Box()
        witness.locks = ()
        for _ in range(50):
            b.v += 1  # read+write, same thread/locks every iteration
        assert racewatch.observed_count() == 2  # one read + one write

    def test_slots_class_wraps_the_slot_descriptor(self, witness):
        class SBox:
            __slots__ = ("v",)

            def __init__(self):
                self.v = 7

        racewatch.watch(SBox, "v")
        b = SBox()
        witness.locks = ()
        assert b.v == 7
        b.v = 8
        assert b.v == 8
        (f,) = racewatch.findings()
        assert f["attr"] == "SBox.v"
        assert {p["write"] for p in f["profiles"]} == {True, False}

    def test_disarmed_witness_records_nothing(self, witness, monkeypatch):
        class Box:
            def __init__(self):
                self.v = 0

        racewatch.watch(Box, "v")
        b = Box()
        monkeypatch.setattr(racewatch, "_INSTALLED", False)
        b.v = 1
        assert b.v == 1  # descriptor still delegates storage
        assert racewatch.observed_count() == 0


class TestRegistration:
    def test_register_is_pending_until_installed(self, monkeypatch):
        monkeypatch.setattr(racewatch, "_INSTALLED", False)
        monkeypatch.setattr(racewatch, "_PENDING", [])

        class Box:
            pass

        racewatch.register(Box, "v")
        assert not isinstance(Box.__dict__.get("v"),
                              racewatch._WatchedAttr)
        assert racewatch._PENDING == [(Box, ("v",))]

    def test_register_instruments_when_armed(self, witness):
        class Box:
            pass

        racewatch.register(Box, "v")
        assert isinstance(Box.__dict__["v"], racewatch._WatchedAttr)


class TestRacyPairs:
    P = [
        {"thread": 1, "locks": ["A"], "write": True},
        {"thread": 2, "locks": ["A"], "write": True},
        {"thread": 2, "locks": [], "write": False},
        {"thread": 1, "locks": [], "write": False},
    ]

    def test_cross_thread_disjoint_with_write_only(self):
        got = racewatch.racy_pairs(self.P)
        # (1,A,w)x(2,[],r) and (2,A,w)x(1,[],r): write vs bare read on
        # the other thread; the read-read and common-lock pairs drop
        assert len(got) == 2
        for a, b in got:
            assert a["thread"] != b["thread"]
            assert a["write"] or b["write"]
            assert not (set(a["locks"]) & set(b["locks"]))


class TestDump:
    def test_dump_round_trip(self, witness, tmp_path):
        class Box:
            def __init__(self):
                self.v = 0

        racewatch.watch(Box, "v")
        b = Box()
        witness.locks = ()
        b.v = 1
        path = racewatch.dump_now(str(tmp_path / "racewatch-1.json"))
        payload = json.loads(pathlib.Path(path).read_text())
        assert payload["observed"] == 1
        (f,) = [a for a in payload["attrs"] if a["attr"] == "Box.v"]
        assert f["profiles"][0]["write"] is True

    def test_no_out_dir_is_a_noop(self, witness, monkeypatch):
        monkeypatch.delenv("M3_TPU_RACEWATCH_OUT", raising=False)
        assert racewatch.dump_now() == ""


def run_gate(*paths):
    return subprocess.run(
        [sys.executable, str(REPO / "scripts" / "race_check.py"),
         *[str(p) for p in paths]],
        capture_output=True, text=True)


def dump(tmp_path, name, attrs, observed=None):
    n = observed if observed is not None else sum(
        len(a["profiles"]) for a in attrs)
    (tmp_path / name).write_text(json.dumps(
        {"pid": 1, "observed": n, "attrs": attrs}))


def attr_entry(ident, profiles):
    return {"attr": ident,
            "threads": len({p["thread"] for p in profiles}),
            "profiles": profiles,
            "racy": [[a, b] for a, b in racewatch.racy_pairs(profiles)]}


class TestRaceCheckGate:
    def test_ledger_blessed_racy_pair_is_green(self, tmp_path):
        # SeriesRegistry._index is a DECLARED lock-free protocol: the
        # witnessed disjoint-lock pair passes by declaration.
        assert "SeriesRegistry._index" in race_rules.load_ledger()
        dump(tmp_path, "racewatch-1.json", [attr_entry(
            "SeriesRegistry._index",
            [{"thread": 1, "locks": [], "write": True},
             {"thread": 2, "locks": [], "write": False}])])
        proc = run_gate(tmp_path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "SeriesRegistry._index" in proc.stdout

    def test_undeclared_racy_pair_fails(self, tmp_path):
        dump(tmp_path, "racewatch-1.json", [attr_entry(
            "NotOnLedger._x",
            [{"thread": 1, "locks": [], "write": True},
             {"thread": 2, "locks": [], "write": False}])])
        proc = run_gate(tmp_path)
        assert proc.returncode == 1, proc.stdout
        assert "UNDECLARED RACY PAIR" in proc.stdout

    def test_locked_pair_matching_the_model_is_green(self, tmp_path):
        model = race_rules.protection_model(str(REPO / "m3_tpu"))
        ident = sorted(model)[0]
        lock = model[ident][0]
        dump(tmp_path, "racewatch-1.json", [attr_entry(
            ident,
            [{"thread": 1, "locks": [lock], "write": True},
             {"thread": 2, "locks": [lock], "write": False}])])
        proc = run_gate(tmp_path)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_locked_pair_on_the_wrong_lock_fails(self, tmp_path):
        model = race_rules.protection_model(str(REPO / "m3_tpu"))
        ident = sorted(model)[0]
        dump(tmp_path, "racewatch-1.json", [attr_entry(
            ident,
            [{"thread": 1, "locks": ["Wrong._mu"], "write": True},
             {"thread": 2, "locks": ["Wrong._mu"], "write": False}])])
        proc = run_gate(tmp_path)
        assert proc.returncode == 1, proc.stdout
        assert "PROTECTION MODEL MISMATCH" in proc.stdout

    def test_refuses_vacuous_pass_nothing_observed(self, tmp_path):
        dump(tmp_path, "racewatch-1.json", [], observed=0)
        proc = run_gate(tmp_path)
        assert proc.returncode == 2
        assert "vacuous" in proc.stdout

    def test_refuses_vacuous_pass_single_threaded(self, tmp_path):
        # Observations happened, but never from two threads: the smokes
        # did not exercise shared state — refuse, don't bless.
        dump(tmp_path, "racewatch-1.json", [attr_entry(
            "Some._attr",
            [{"thread": 1, "locks": [], "write": True}])])
        proc = run_gate(tmp_path)
        assert proc.returncode == 2
        assert "vacuous" in proc.stdout

    def test_refuses_empty_dump_dir(self, tmp_path):
        proc = run_gate(tmp_path)
        assert proc.returncode == 2


class TestAutoInstallEndToEnd:
    """The wired path: M3_TPU_RACEWATCH=1 arms the witness at package
    import, product register() calls instrument SeriesRegistry, real
    threaded traffic produces a dump, and the gate accepts it."""

    def test_registry_traffic_dumps_and_gate_accepts(self, tmp_path):
        code = (
            "import threading\n"
            "from m3_tpu.storage.series import SeriesRegistry\n"
            "from m3_tpu.utils import racewatch\n"
            "assert racewatch.installed()\n"
            "reg = SeriesRegistry()\n"
            "def work(base):\n"
            "    for i in range(32):\n"
            "        reg.get_or_create(b'%d-%d' % (base, i), None)\n"
            "        reg.get(b'%d-%d' % (base, i))\n"
            "ts = [threading.Thread(target=work, args=(k,))"
            " for k in (1, 2)]\n"
            "[t.start() for t in ts]\n"
            "work(0)\n"
            "[t.join() for t in ts]\n"
            "assert racewatch.observed_count() > 0\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            env={**__import__("os").environ,
                 "M3_TPU_RACEWATCH": "1",
                 "M3_TPU_RACEWATCH_OUT": str(tmp_path),
                 "PYTHONPATH": str(REPO)})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        dumps = list(tmp_path.glob("racewatch-*.json"))
        assert len(dumps) == 1
        payload = json.loads(dumps[0].read_text())
        assert payload["observed"] > 0
        idents = {a["attr"] for a in payload["attrs"]}
        assert "SeriesRegistry._index" in idents
        gate = run_gate(tmp_path)
        assert gate.returncode == 0, gate.stdout + gate.stderr
