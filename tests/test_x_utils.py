"""x-utility tests: cost enforcer, lockfile, panicmon, tag serialization,
runtime options manager (reference: src/x/{cost,lockfile,panicmon,
serialize}, src/dbnode/runtime + kvconfig)."""

import json
import os
import subprocess
import sys
import time

import pytest

from m3_tpu.cluster import kv as cluster_kv
from m3_tpu.storage.runtime import (
    RuntimeOptions,
    RuntimeOptionsManager,
    WRITE_NEW_SERIES_LIMIT_PER_SECOND,
    watch_kv_runtime_options,
)
from m3_tpu.utils import serialize
from m3_tpu.utils.cost import CostLimitExceeded, Enforcer
from m3_tpu.utils.lockfile import Lockfile, LockError
from m3_tpu.utils.panicmon import Panicmon


class TestCostEnforcer:
    def test_limit_enforced(self):
        e = Enforcer(limit=100)
        e.add(60)
        with pytest.raises(CostLimitExceeded):
            e.add(50)

    def test_child_chains_to_parent(self):
        glob = Enforcer(limit=100, name="global")
        q1 = glob.child(limit=80, name="q1")
        q2 = glob.child(limit=80, name="q2")
        q1.add(60)
        with pytest.raises(CostLimitExceeded):
            q2.add(50)  # under q2's own limit, over global
        q1.release(60)
        q2.add(50)

    def test_release(self):
        e = Enforcer(limit=10)
        e.add(8)
        e.release(8)
        e.add(9)
        assert e.current() == 9


class TestLockfile:
    def test_exclusive(self, tmp_path):
        path = str(tmp_path / "node.lock")
        with Lockfile(path):
            # A second process must fail to take it.
            rc = subprocess.run(
                [sys.executable, "-c",
                 "import sys; sys.path.insert(0, '.');"
                 "from m3_tpu.utils.lockfile import Lockfile, LockError\n"
                 "try:\n"
                 f"    Lockfile({path!r}).acquire()\n"
                 "    sys.exit(0)\n"
                 "except LockError:\n"
                 "    sys.exit(42)"],
                cwd="/root/repo").returncode
            assert rc == 42
        # Released: take it again.
        Lockfile(path).acquire().release()


@pytest.mark.slow
class TestPanicmon:
    def test_restart_on_crash(self):
        mon = Panicmon([sys.executable, "-c", "import sys; sys.exit(3)"],
                       restart_on_crash=True, max_restarts=2, backoff_s=0.05)
        mon.start()
        # Three interpreter startups under load: same generous deadline as
        # test_clean_exit_no_restart.
        deadline = time.time() + 60
        while mon.restarts < 2 and time.time() < deadline:
            time.sleep(0.05)
        mon.stop()
        assert mon.restarts == 2
        assert all(rc == 3 for rc in mon.exit_codes[:3])

    def test_clean_exit_no_restart(self):
        mon = Panicmon([sys.executable, "-c", "pass"],
                       restart_on_crash=True, backoff_s=0.05)
        mon.start()
        # Generous deadline: interpreter startup can take tens of seconds on
        # a loaded machine, and stop() before the clean exit records the
        # TERM signal as the exit code (observed flake under a concurrent
        # bench run).
        deadline = time.time() + 60
        while not mon.exit_codes and time.time() < deadline:
            time.sleep(0.05)
        mon.stop()
        assert mon.exit_codes[0] == 0
        assert mon.restarts == 0


class TestTagSerialize:
    def test_roundtrip(self):
        tags = {b"host": b"web-01", b"dc": b"east", b"empty": b""}
        buf = serialize.encode_tags(tags)
        assert serialize.decode_tags(buf) == tags

    def test_header_validated(self):
        with pytest.raises(serialize.TagEncodeError):
            serialize.decode_tags(b"\x00\x00\x00\x00")
        buf = serialize.encode_tags({b"a": b"b"})
        with pytest.raises(serialize.TagEncodeError):
            serialize.decode_tags(buf[:-1])  # truncated
        with pytest.raises(serialize.TagEncodeError):
            serialize.decode_tags(buf + b"x")  # trailing

    def test_deterministic_sorted(self):
        b1 = serialize.encode_tags({b"b": b"2", b"a": b"1"})
        b2 = serialize.encode_tags({b"a": b"1", b"b": b"2"})
        assert b1 == b2


class TestRuntimeOptions:
    def test_listeners_fire_on_update(self):
        mgr = RuntimeOptionsManager()
        seen = []
        mgr.register_listener(lambda o: seen.append(o.write_new_series_limit_per_second))
        assert seen == [0]  # fired with current on register
        mgr.update(write_new_series_limit_per_second=500)
        assert seen[-1] == 500

    def test_kv_watch_folds_keys(self):
        store = cluster_kv.MemStore()
        mgr = RuntimeOptionsManager()
        watch_kv_runtime_options(store, mgr)
        store.set(f"_kvconfig/{WRITE_NEW_SERIES_LIMIT_PER_SECOND}",
                  json.dumps(1234).encode())
        assert mgr.get().write_new_series_limit_per_second == 1234
        # Pre-existing value applies on (re)wire.
        mgr2 = RuntimeOptionsManager()
        watch_kv_runtime_options(store, mgr2)
        assert mgr2.get().write_new_series_limit_per_second == 1234
