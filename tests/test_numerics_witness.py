"""Runtime numerics witness (utils/numwatch.py) + the
scripts/numerics_check.py gate logic: live-lane NaN/inf trips,
padding-lane value trips, masked-pad passes, the aggregator count-0
zero convention, dump round-trips, and the statically-derived
acceptance set (m3_tpu/analysis/numeric_rules.accepted_witness)."""

import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from m3_tpu.analysis import numeric_rules
from m3_tpu.utils import numwatch

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture
def witness():
    numwatch.install()
    numwatch.reset()
    yield numwatch
    numwatch.reset()
    numwatch.uninstall()


def kinds(w):
    return sorted((f["site"], f["kind"]) for f in w.findings())


class TestObserveResult:
    def test_masked_pad_passes(self, witness):
        # The contract shape: live lanes finite, padding rows all-NaN.
        plane = np.full((8, 16), np.nan)
        plane[:5, :12] = 1.5
        witness.observe_result("plan", plane, live_rows=5, live_cols=12)
        assert witness.findings() == []
        assert witness.observed_count() == 1

    def test_nan_in_live_lane_trips(self, witness):
        plane = np.full((8, 16), np.nan)
        plane[:5, :12] = 1.5
        plane[2, 3] = np.nan
        witness.observe_result("plan", plane, live_rows=5, live_cols=12)
        assert kinds(witness) == [("plan", "nan-live")]

    def test_inf_in_live_lane_trips(self, witness):
        plane = np.full((8, 16), np.nan)
        plane[:5, :12] = 1.5
        plane[0, 0] = np.inf
        witness.observe_result("plan", plane, live_rows=5, live_cols=12)
        assert kinds(witness) == [("plan", "inf-live")]

    def test_padding_lane_value_trips(self, witness):
        # A finite value in a padding ROW: the unmasked-gather leak
        # shape the witness exists to catch.
        plane = np.full((8, 16), np.nan)
        plane[:5, :12] = 1.5
        plane[6, 2] = 42.0
        witness.observe_result("plan", plane, live_rows=5, live_cols=12)
        assert kinds(witness) == [("plan", "pad-finite")]

    def test_column_padding_is_time_slack_not_a_finding(self, witness):
        # Presence-style outputs (absent_over_time) legitimately fill
        # pad COLUMNS; only pad ROWS carry the NaN contract.
        plane = np.full((1, 16), np.nan)
        plane[0, :12] = 1.0
        plane[0, 14] = 1.0  # pad column, finite — sliced by the host
        witness.observe_result("plan", plane, live_rows=1, live_cols=12)
        assert witness.findings() == []

    def test_counts_aggregate_per_site_kind(self, witness):
        plane = np.full((4, 4), np.nan)
        plane[0, 0] = np.inf
        witness.observe_result("plan", plane, live_rows=1, live_cols=4)
        witness.observe_result("plan", plane, live_rows=1, live_cols=4)
        (f,) = [f for f in witness.findings() if f["kind"] == "inf-live"]
        assert f["count"] == 2

    def test_scalar_and_vector_planes_handled(self, witness):
        witness.observe_result("plan", np.float64(3.0))
        witness.observe_result("plan", np.array([1.0, 2.0]))
        assert witness.findings() == []
        witness.observe_result("plan", np.float64(np.nan))
        assert kinds(witness) == [("plan", "nan-live")]

    def test_disabled_witness_is_free(self):
        numwatch.uninstall()
        numwatch.reset()
        numwatch.observe_result("plan", np.full((2, 2), np.inf))
        assert numwatch.findings() == []
        assert numwatch.observed_count() == 0


class TestObserveRows:
    def test_count0_zero_convention_passes(self, witness):
        vals = np.array([[1.0, 2.0], [0.0, 0.0]])
        witness.observe_rows("agg_flush", vals, np.array([True, False]))
        assert witness.findings() == []

    def test_pad_nonzero_trips(self, witness):
        vals = np.array([[1.0, 2.0], [0.0, 7.0]])
        witness.observe_rows("agg_flush", vals, np.array([True, False]))
        assert kinds(witness) == [("agg_flush", "pad-nonzero")]

    def test_live_nan_recorded(self, witness):
        vals = np.array([[np.nan, 2.0], [0.0, 0.0]])
        witness.observe_rows("agg_flush", vals, np.array([True, False]))
        assert kinds(witness) == [("agg_flush", "nan-live")]


class TestAggFlushHookEndToEnd:
    """The real observation point: exact_quantile_values with the
    witness armed."""

    def test_clean_buckets_observe_no_findings(self, witness):
        from m3_tpu.parallel import agg_flush

        buckets = [np.array([3.0, 1.0, 2.0]), np.array([]),
                   np.array([5.0])]
        counts = np.array([3, 0, 1])
        vals = agg_flush.exact_quantile_values(buckets, counts, (0.5, 0.99))
        assert witness.observed_count() >= 1
        assert (vals[1] == 0.0).all()
        assert [f for f in witness.findings()
                if f["kind"] in ("pad-nonzero", "inf-live")] == []

    def test_nan_bucket_records_accepted_nan_live(self, witness):
        from m3_tpu.parallel import agg_flush

        buckets = [np.array([np.nan, np.nan])]
        counts = np.array([2])
        agg_flush.exact_quantile_values(buckets, counts, (0.99,))
        got = kinds(witness)
        assert ("agg_flush", "nan-live") in got
        # ... and the static pass ACCEPTS that kind at that site
        accepted = numeric_rules.accepted_witness(str(REPO / "m3_tpu"))
        assert ("agg_flush", "nan-live") in accepted


class TestDumpAndGate:
    def test_dump_round_trip(self, witness, tmp_path):
        plane = np.full((4, 4), np.nan)
        plane[:2, :] = 1.0   # live lanes clean
        plane[3, 0] = 5.0    # the padding-row leak
        witness.observe_result("plan", plane, live_rows=2, live_cols=4)
        path = witness.dump_now(str(tmp_path / "numerics-1.json"))
        payload = json.loads(pathlib.Path(path).read_text())
        assert payload["observed"] == 1
        assert payload["findings"][0]["kind"] == "pad-finite"

    def test_accepted_set_is_derived_not_listed(self):
        accepted = numeric_rules.accepted_witness(str(REPO / "m3_tpu"))
        # NaN-as-missing is provable at both sites; the padding kinds
        # are NEVER accepted anywhere.
        assert ("plan", "nan-live") in accepted
        assert ("agg_flush", "nan-live") in accepted
        assert not any(k in ("pad-finite", "pad-nonzero")
                       for _s, k in accepted)

    def test_unaccepted_filter(self):
        witnessed = [
            {"site": "plan", "kind": "nan-live", "count": 3, "detail": ""},
            {"site": "plan", "kind": "pad-finite", "count": 1,
             "detail": ""},
        ]
        accepted = {("plan", "nan-live")}
        bad = numwatch.unaccepted(witnessed, accepted)
        assert [f["kind"] for f in bad] == ["pad-finite"]

    def _run_check(self, tmp_path):
        return subprocess.run(
            [sys.executable, str(REPO / "scripts" / "numerics_check.py"),
             str(tmp_path)],
            capture_output=True, text=True)

    def test_check_script_green_on_accepted_findings(self, tmp_path):
        (tmp_path / "numerics-1.json").write_text(json.dumps({
            "pid": 1, "observed": 5,
            "findings": [{"site": "plan", "kind": "nan-live", "count": 4,
                          "detail": "NaN in live lanes"}]}))
        proc = self._run_check(tmp_path)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_check_script_fails_hard_on_padding_violation(self, tmp_path):
        (tmp_path / "numerics-1.json").write_text(json.dumps({
            "pid": 1, "observed": 5,
            "findings": [{"site": "plan", "kind": "pad-finite", "count": 1,
                          "detail": "finite value in padding rows"}]}))
        proc = self._run_check(tmp_path)
        assert proc.returncode == 2, proc.stdout
        assert "PADDING CONTRACT VIOLATION" in proc.stdout

    def test_check_script_fails_on_unaccepted_site_kind(self, tmp_path):
        (tmp_path / "numerics-1.json").write_text(json.dumps({
            "pid": 1, "observed": 5,
            "findings": [{"site": "agg_flush", "kind": "inf-live",
                          "count": 1, "detail": "inf in live rows"}]}))
        proc = self._run_check(tmp_path)
        assert proc.returncode == 1, proc.stdout
        assert "UNACCEPTED" in proc.stdout

    def test_check_script_refuses_vacuous_pass(self, tmp_path):
        (tmp_path / "numerics-1.json").write_text(json.dumps({
            "pid": 1, "observed": 0, "findings": []}))
        proc = self._run_check(tmp_path)
        assert proc.returncode == 2
        assert (tmp_path / "nothing").exists() is False


class TestPlanHookEndToEnd:
    """The compiled-plan observation point through the real executor:
    compiled queries under the witness observe padded planes, and every
    finding stays inside the static-accepted set (the numerics_check
    tier's contract, in-process)."""

    def test_compiled_queries_witnessed_within_accepted(self, witness,
                                                        monkeypatch):
        from test_plan_compile import make_storage, START, END, STEP
        from m3_tpu.query import Engine
        from m3_tpu.query import plan as qplan

        monkeypatch.setattr(qplan, "PLAN_MIN_CELLS", 1)
        eng = Engine(make_storage(7))
        # one query per padded-output family: grouped exact sum (group
        # rows pad), rangefunc root (series rows pad), vv binary
        # (match-row pad), topk (masked winners + host row filter)
        for q in ("sum by (host) (m)", "rate(m[5m])",
                  "m * on(host, i) b", "topk(2, m)"):
            eng.execute_range(q, START, END, STEP)
        assert witness.observed_count() >= 4
        accepted = numeric_rules.accepted_witness(str(REPO / "m3_tpu"))
        bad = numwatch.unaccepted(witness.findings(), accepted)
        assert bad == [], f"witnessed findings outside accepted: {bad}"


class TestPadRowFullWidthScan:
    """Review-pass regression: a leak landing in a padding row at a
    padding COLUMN is still a pad-finite finding — the pad-row scan
    covers the full time extent, not just the live columns."""

    def test_pad_row_pad_column_leak_trips(self, witness):
        plane = np.full((8, 16), np.nan)
        plane[:5, :12] = 1.5
        plane[6, 14] = 42.0  # pad row x pad column
        witness.observe_result("plan", plane, live_rows=5, live_cols=12)
        assert kinds(witness) == [("plan", "pad-finite")]
