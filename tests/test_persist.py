"""Persistence: fileset write/read/seek invariants + commitlog WAL replay."""

import os

import numpy as np
import pytest

from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.persist import commitlog as cl
from m3_tpu.persist.fs import (
    CHECKPOINT_FILE,
    FilesetReader,
    PersistManager,
    Seeker,
    fileset_complete,
)
from m3_tpu.storage.block import encode_block
from m3_tpu.storage.database import Database
from m3_tpu.storage.namespace import NamespaceOptions
from m3_tpu.storage.series import SeriesRegistry
from m3_tpu.utils import xtime
from m3_tpu.utils.bloom import BloomFilter

BLOCK = 2 * xtime.HOUR
T0 = 1_600_000_000 * xtime.SECOND
T0_BLOCK = T0 - T0 % BLOCK


def make_block(rng, n=12, w=30):
    reg = SeriesRegistry()
    ids = [f"srv.{i}.latency".encode() for i in range(n)]
    for sid in ids:
        reg.get_or_create(sid)
    ts = T0_BLOCK + np.arange(w, dtype=np.int64)[None, :] * 10 * xtime.SECOND + np.zeros((n, 1), np.int64)
    vals = rng.integers(0, 50, size=(n, w)).astype(np.float64)
    blk = encode_block(T0_BLOCK, np.arange(n, dtype=np.int32), ts, vals, np.full(n, w, np.int32))
    return reg, ids, ts, vals, blk


def test_bloom_filter(rng):
    bf = BloomFilter.for_capacity(1000, 0.01)
    items = [f"id-{i}".encode() for i in range(1000)]
    bf.add_batch(items)
    assert all(i in bf for i in items)
    fp = sum(f"other-{i}".encode() in bf for i in range(1000))
    assert fp < 50
    bf2 = BloomFilter.frombytes(bf.tobytes(), bf.m, bf.k)
    assert items[0] in bf2


def test_fileset_roundtrip(tmp_path, rng):
    reg, ids, ts, vals, blk = make_block(rng)
    pm = PersistManager(str(tmp_path))
    path = pm.write_block(b"ns1", 7, blk, reg)
    assert fileset_complete(path)
    assert pm.list_filesets(b"ns1", 7) == [(T0_BLOCK, path)]
    assert pm.shards_with_data(b"ns1") == [7]

    reader = FilesetReader(path)
    blk2, row_ids = reader.to_block()
    assert set(row_ids) == set(ids)
    for row, sid in enumerate(row_ids):
        orig_row = ids.index(sid)
        t, v = blk2.read(row)
        np.testing.assert_array_equal(t, ts[orig_row])
        np.testing.assert_allclose(v, vals[orig_row])


def test_fileset_incomplete_without_checkpoint(tmp_path, rng):
    reg, ids, ts, vals, blk = make_block(rng)
    pm = PersistManager(str(tmp_path))
    path = pm.write_block(b"ns1", 0, blk, reg)
    os.remove(os.path.join(path, CHECKPOINT_FILE))
    assert not fileset_complete(path)
    with pytest.raises(FileNotFoundError):
        FilesetReader(path)
    assert pm.list_filesets(b"ns1", 0) == []


def test_seeker_bloom_and_lookup(tmp_path, rng):
    reg, ids, ts, vals, blk = make_block(rng)
    pm = PersistManager(str(tmp_path))
    path = pm.write_block(b"ns1", 1, blk, reg)
    seeker = Seeker(path)
    row = seeker.seek(ids[5])
    assert row is not None
    words, nbits, npoints = row
    assert npoints == 30
    assert seeker.seek(b"nope") is None


def test_snapshot_volumes(tmp_path, rng):
    reg, ids, ts, vals, blk = make_block(rng)
    pm = PersistManager(str(tmp_path))
    pm.write_snapshot(b"ns1", 2, blk, reg, version=1)
    pm.write_snapshot(b"ns1", 2, blk, reg, version=2)
    snaps = pm.list_snapshots(b"ns1", 2)
    assert [(s[0], s[1]) for s in snaps] == [(T0_BLOCK, 1), (T0_BLOCK, 2)]
    assert pm.list_filesets(b"ns1", 2) == []


def test_commitlog_roundtrip_and_torn_tail(tmp_path):
    d = str(tmp_path / "cl")
    log = cl.CommitLog(d, strategy=cl.Strategy.WRITE_WAIT)
    log.write(b"ns1", b"a", 100, 1.5)
    log.write(b"ns1", b"b", 110, 2.5)
    log.write(b"ns2", b"a", 120, 3.5)
    log.rotate()
    log.write(b"ns1", b"a", 130, 4.5)
    log.close()

    entries = list(cl.replay(d))
    assert entries == [
        (b"ns1", b"a", 100, 1.5),
        (b"ns1", b"b", 110, 2.5),
        (b"ns2", b"a", 120, 3.5),
        (b"ns1", b"a", 130, 4.5),
    ]

    # Torn tail: truncate the last file mid-chunk; replay drops only the tail.
    files = sorted(os.listdir(d))
    last = os.path.join(d, files[-1])
    size = os.path.getsize(last)
    with open(last, "ab") as f:
        f.write(b"\x99\x00\x00\x00garbage")
    entries2 = list(cl.replay(d))
    assert entries2 == entries


def test_commitlog_write_behind_flush_on_interval(tmp_path):
    now = {"t": 0}
    d = str(tmp_path / "cl")
    log = cl.CommitLog(d, strategy=cl.Strategy.WRITE_BEHIND,
                       flush_interval_ns=10, clock=lambda: now["t"])
    log.write(b"ns", b"x", 1, 1.0)
    assert list(cl.replay(d)) == []  # buffered, not yet durable
    now["t"] = 20
    log.write(b"ns", b"x", 2, 2.0)  # interval elapsed -> flush
    assert len(list(cl.replay(d))) == 2
    log.close()


def test_database_flush_rotates_commitlog(tmp_path):
    now = {"t": T0}
    log = cl.CommitLog(str(tmp_path / "cl"), strategy=cl.Strategy.WRITE_WAIT)
    db = Database(ShardSet(4), commitlog=log, clock=lambda: now["t"])
    db.create_namespace(b"default", NamespaceOptions(index_enabled=False))
    for i in range(5):
        db.write(b"default", b"metric-a", T0 + i * 10 * xtime.SECOND, float(i))
    now["t"] = T0_BLOCK + BLOCK + 11 * xtime.MINUTE
    db.tick()
    pm = PersistManager(str(tmp_path / "data"))
    n = db.flush(pm)
    assert n == 1
    files = pm.list_filesets(b"default", db.shard_set.lookup(b"metric-a"))
    assert len(files) == 1
    # Commit log rotated after flush.
    assert len(log.files()) == 2
    log.close()
