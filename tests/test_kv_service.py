"""Networked KV service: cross-process metadata plane (reference:
src/cluster/kv/etcd/store.go semantics — versioned CAS KV with watch
streams; src/cluster/etcd/watchmanager/watch_manager.go). RemoteStore must
be a drop-in for MemStore so placements/elections/flush-times work
identically across processes."""

import time

import pytest

from m3_tpu.cluster import kv as cluster_kv
from m3_tpu.cluster.kv_service import KVServer, RemoteStore
from m3_tpu.cluster.placement import Instance, PlacementService
from m3_tpu.services import config as svc_config
from m3_tpu.services import run as svc_run


def _await(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


@pytest.fixture
def server():
    srv = KVServer().start()
    yield srv
    srv.close()


class TestRemoteStoreParity:
    def test_get_set_versioning(self, server):
        st = RemoteStore(server.endpoint)
        assert st.get("k") is None
        assert st.set("k", b"v1") == 1
        assert st.set("k", b"v2") == 2
        v = st.get("k")
        assert v.data == b"v2" and v.version == 2

    def test_setnx_and_cas(self, server):
        st = RemoteStore(server.endpoint)
        assert st.set_if_not_exists("k", b"a") == 1
        with pytest.raises(KeyError):
            st.set_if_not_exists("k", b"b")
        assert st.check_and_set("k", 1, b"c") == 2
        with pytest.raises(ValueError):
            st.check_and_set("k", 1, b"d")  # stale version
        with pytest.raises(ValueError):
            st.check_and_set("new", 5, b"x")  # 0 means not-exists

    def test_delete_and_keys(self, server):
        st = RemoteStore(server.endpoint)
        st.set("a/1", b"x")
        st.set("a/2", b"y")
        st.set("b/1", b"z")
        assert st.keys("a/") == ["a/1", "a/2"]
        assert st.delete("a/1") is not None
        assert st.delete("a/1") is None
        assert st.keys("a/") == ["a/2"]

    def test_reconnect_after_server_side_close(self, server):
        st = RemoteStore(server.endpoint)
        st.set("k", b"v")
        # Kill the pooled connection server-side; next request reconnects.
        st._sock.close()
        assert st.get("k").data == b"v"


class TestWatchPush:
    def test_watch_fires_across_clients(self, server):
        writer = RemoteStore(server.endpoint)
        reader = RemoteStore(server.endpoint)
        w = reader.watch("key")
        writer.set("key", b"v1")
        assert w.wait(timeout=5.0)
        assert w.get().data == b"v1"
        writer.set("key", b"v2")
        assert w.wait(timeout=5.0)
        assert w.get().version == 2

    def test_on_change_pushes_values(self, server):
        writer = RemoteStore(server.endpoint)
        reader = RemoteStore(server.endpoint)
        seen = []
        reader.on_change("cfg", lambda key, v: seen.append((v.version, v.data)))
        writer.set("cfg", b"one")
        assert _await(lambda: (1, b"one") in seen)
        writer.set("cfg", b"two")
        assert _await(lambda: (2, b"two") in seen)

    def test_watch_delivers_current_value_immediately(self, server):
        writer = RemoteStore(server.endpoint)
        writer.set("pre", b"existing")
        reader = RemoteStore(server.endpoint)
        seen = []
        reader.on_change("pre", lambda key, v: seen.append(v.data))
        assert _await(lambda: b"existing" in seen)


class TestServicesOverNetworkedKV:
    def test_election_and_flush_times_across_processes(self, server):
        """LeaderService + FlushTimesManager work unchanged on RemoteStore
        (the point of interface parity: one KV process serves the cluster)."""
        from m3_tpu.aggregator import FlushTimesManager
        from m3_tpu.cluster.services import LeaderService

        st_a = RemoteStore(server.endpoint)
        st_b = RemoteStore(server.endpoint)
        clock = lambda: time.time_ns()
        la = LeaderService(st_a, "e1", "inst-a", clock=clock)
        lb = LeaderService(st_b, "e1", "inst-b", clock=clock)
        from m3_tpu.cluster.services import CampaignState

        assert la.campaign() == CampaignState.LEADER
        assert lb.campaign() == CampaignState.FOLLOWER
        assert lb.leader() == "inst-a"
        fa = FlushTimesManager(st_a, "ss")
        fb = FlushTimesManager(st_b, "ss")
        fa.store(0, {10_000_000_000: 123})
        assert _await(lambda: fb.get(0).get(10_000_000_000) == 123)

    def test_aggregator_placement_watch_assigns_shards(self, server):
        """Placement written to the KV service propagates to running
        aggregator instances via watch: shard ownership changes without
        restart (aggregator.go:307)."""
        admin = RemoteStore(server.endpoint)
        psvc = PlacementService(admin, "_placement/agg")
        psvc.init([Instance("agg-a", "a:1"), Instance("agg-b", "b:1")],
                  num_shards=8, replica_factor=1)
        handles = {}
        assigns = {"agg-a": [], "agg-b": []}
        try:
            for iid in ("agg-a", "agg-b"):
                cfg = svc_config.load_dict({
                    "instance_id": iid, "num_shards": 8,
                    "kv_endpoint": server.endpoint,
                    "placement_key": "_placement/agg",
                    "election_id": f"e-{iid}",
                    "flush_interval": "10s",
                }, "aggregator")
                handles[iid] = svc_run.run_aggregator(
                    cfg, on_placement=assigns[iid].append)
            assert _await(lambda: assigns["agg-a"] and assigns["agg-b"])
            a_owned = set(handles["agg-a"].aggregator.owned_shards())
            b_owned = set(handles["agg-b"].aggregator.owned_shards())
            assert a_owned | b_owned == set(range(8))
            assert a_owned.isdisjoint(b_owned)
            # Placement change: drop agg-b; its shards move to agg-a, both
            # instances observe it via watch push.
            psvc.remove_instance("agg-b")
            assert _await(
                lambda: set(handles["agg-a"].aggregator.owned_shards())
                == set(range(8)))
            assert _await(
                lambda: handles["agg-b"].aggregator.owned_shards() == [])
        finally:
            for h in handles.values():
                h.close()


class TestClusterClient:
    """Composed cluster client (reference: src/cluster/client/client.go +
    etcd configservice client): one endpoint yields KV, scoped stores,
    services, elections, and placements."""

    def test_scoped_stores_isolate(self, server):
        from m3_tpu.cluster.client import ClusterClient

        c1 = ClusterClient(endpoint=server.endpoint, zone="z1", env="prod")
        c2 = ClusterClient(endpoint=server.endpoint, zone="z2", env="prod")
        c1.kv().set("cfg", b"one")
        c2.kv().set("cfg", b"two")
        assert c1.kv().get("cfg").data == b"one"
        assert c2.kv().get("cfg").data == b"two"
        sub = c1.store("rules")
        sub.set("r1", b"x")
        assert sub.keys() == ["r1"]
        assert c1.kv().get("rules/r1").data == b"x"
        c1.close()
        c2.close()

    def test_scoped_watch_pushes(self, server):
        from m3_tpu.cluster.client import ClusterClient

        ca = ClusterClient(endpoint=server.endpoint, zone="zz")
        cb = ClusterClient(endpoint=server.endpoint, zone="zz")
        seen = []
        ca.kv().on_change("watched", lambda k, v: seen.append(v.data))
        cb.kv().set("watched", b"pushed")
        assert _await(lambda: b"pushed" in seen)
        ca.close()
        cb.close()

    def test_composed_services_over_one_endpoint(self, server):
        from m3_tpu.cluster.client import ClusterClient
        from m3_tpu.cluster.placement import Instance
        from m3_tpu.cluster.services import CampaignState, ServiceInstance

        clock = lambda: time.time_ns()
        c = ClusterClient(endpoint=server.endpoint)
        svcs = c.services(clock=clock)
        svcs.advertise("m3dbnode", ServiceInstance("n1", "h1:9000"))
        assert [i.instance_id for i in svcs.instances("m3dbnode")] == ["n1"]
        leader = c.leader_service("e1", "n1", clock=clock)
        assert leader.campaign() == CampaignState.LEADER
        psvc = c.placement_service("m3aggregator")
        psvc.init([Instance("a", "a:1")], num_shards=4, replica_factor=1)
        assert set(psvc.get().instances) == {"a"}
        # Distinct per-service placements don't collide.
        assert c.placement_service("m3db").get() is None
        c.close()
