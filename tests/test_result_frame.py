"""Columnar result-frame proof: the columnar Prometheus JSON renderer
(query/render.py — zero per-series Python dicts on the path) is
BYTE-identical to the retained per-series oracle `render_result_ref`
across the whole compiled-vs-oracle query corpus, adversarial value
grids (shortest-decimal edge cases, negative zero, 2^53 boundaries,
all-NaN rows, empty results, unicode labels), and the real HTTP
surface (the coordinator serves the columnar bytes verbatim)."""

import json
import urllib.request

import numpy as np
import pytest

from m3_tpu.query import Engine
from m3_tpu.query import plan as qplan
from m3_tpu.query import render
from m3_tpu.query.block import Block, BlockMeta
from m3_tpu.query.model import Tags

from test_plan_compile import (  # noqa: F401 — shared corpus fixture
    COMPILED_QUERIES, FALLBACK_QUERIES, START, END, STEP, make_storage,
)

S = 1_000_000_000
META = BlockMeta(1_700_000_000 * S, 30 * S, 12)


def tags_of(i, extra=None):
    d = {b"__name__": b"m", b"host": b"h%d" % (i % 3), b"i": str(i).encode()}
    if extra:
        d.update(extra)
    return Tags.of(d)


def assert_identical(block, instant=False):
    got = (render.prom_vector_bytes(block) if instant
           else render.prom_matrix_bytes(block))
    ref = render.render_result_ref(block, instant=instant)
    assert got == ref, (
        f"columnar frame diverged ({len(got)} vs {len(ref)} bytes); "
        f"first diff at "
        f"{next((i for i, (a, b) in enumerate(zip(got, ref)) if a != b), -1)}")
    json.loads(got)  # and it is valid JSON


@pytest.fixture
def no_floor(monkeypatch):
    monkeypatch.setattr(qplan, "PLAN_MIN_CELLS", 1)


class TestValueFormatting:
    def test_adversarial_grid(self):
        vals = np.array([
            [0.1, -0.0, 2.0, 1e16, 1e-4, 1e-5, np.nan, np.inf, -1e17,
             123.456, 0.30000000000000004, 2.0 ** 53],
            [2.0 ** 53 - 1, -(2.0 ** 53), 9007199254740994.0, 1.5, -7.0,
             0.0, -0.0, 1e15, 5e-324, -5e-324, 1.7976931348623157e308,
             -1e300],
            [np.nan] * 12,   # all-NaN row: dropped by both renderers
        ])
        tags = [tags_of(i, {b"u": "ünicodé \"q\"\\".encode()})
                for i in range(3)]
        assert_identical(Block(META, tags, vals))
        assert_identical(Block(META, tags, vals), instant=True)

    def test_f32_planes(self):
        # Compiled-route result planes are f32: the ref casts per value,
        # the columnar path as a matrix — must agree bytewise.
        rng = np.random.default_rng(3)
        vals = (1e9 + np.cumsum(rng.poisson(5.0, (6, 12)),
                                axis=1)).astype(np.float32)
        assert_identical(Block(META, [tags_of(i) for i in range(6)], vals))

    def test_empty_and_all_nan(self):
        assert_identical(Block(META, [], np.zeros((0, 12))))
        assert_identical(Block(META, [], np.zeros((0, 12))), instant=True)
        vals = np.full((4, 12), np.nan)
        assert_identical(Block(META, [tags_of(i) for i in range(4)], vals))

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzzed_magnitudes(self, seed):
        rng = np.random.default_rng(seed)
        scale = 10.0 ** rng.integers(-10, 20)
        vals = rng.normal(0, scale, (16, 12))
        vals[rng.random((16, 12)) < 0.25] = np.nan
        if seed % 2:
            vals = np.round(vals)
        assert_identical(Block(META, [tags_of(i) for i in range(16)], vals))
        assert_identical(Block(META, [tags_of(i) for i in range(16)], vals),
                         instant=True)


class TestCorpusByteIdentity:
    """The satellite property: across the whole compiled-vs-oracle
    corpus, the columnar HTTP JSON is byte-identical to the per-series
    oracle — both for compiled-route (f32, lazily materialized) and
    interpreter-route blocks."""

    @pytest.mark.parametrize("seed", range(3))
    def test_whole_corpus(self, seed, no_floor):
        eng = Engine(make_storage(seed))
        for q in COMPILED_QUERIES + FALLBACK_QUERIES:
            block = eng.execute_range(q, START, END, STEP)
            got = render.prom_matrix_bytes(block)
            assert got == render.render_result_ref(block), q
            got_i = render.prom_vector_bytes(block)
            assert got_i == render.render_result_ref(block, instant=True), q


class TestHTTPServesColumnar:
    def test_query_range_bytes_are_oracle_bytes(self, no_floor):
        from m3_tpu.coordinator.http_api import HTTPApi

        eng = Engine(make_storage(42))
        api = HTTPApi(eng).serve()
        try:
            from urllib.parse import urlencode

            for q in ("sum by (host) (rate(m[5m]))", "topk(3, m)",
                      "max_over_time(rate(m[5m])[10m:1m])", "m and b"):
                params = {"query": q, "start": START / S, "end": END / S,
                          "step": "30"}
                with urllib.request.urlopen(
                        f"{api.endpoint}/api/v1/query_range?"
                        f"{urlencode(params)}") as resp:
                    got = resp.read()
                block = eng.execute_range(q, START, END, STEP)
                assert got == render.render_result_ref(block), q
            # instant vector
            with urllib.request.urlopen(
                    f"{api.endpoint}/api/v1/query?"
                    f"{urlencode({'query': 'sum by (host) (m)', 'time': END / S})}"
            ) as resp:
                got = resp.read()
            block = eng.execute_instant("sum by (host) (m)", END)
            assert got == render.render_result_ref(block, instant=True)
        finally:
            api.close()
