"""m3msg pub/sub + matcher + collector tests (reference behaviors:
at-least-once delivery with acks, drop-oldest buffering, KV-watched rule
matching with cache invalidation, end-to-end collector->aggregator flow)."""

import threading
import time

import pytest

from m3_tpu.aggregator import Aggregator, AggregatorClient, CaptureHandler
from m3_tpu.cluster import kv as cluster_kv
from m3_tpu.cluster.placement import Instance, initial_placement
from m3_tpu.collector import Reporter
from m3_tpu.metrics import aggregation as magg
from m3_tpu.metrics import id as metric_id
from m3_tpu.metrics.filters import TagsFilter
from m3_tpu.metrics.matcher import Matcher, RuleSetStore
from m3_tpu.metrics.pipeline import Op, Pipeline
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.rules import (
    MappingRuleSnapshot,
    RollupRuleSnapshot,
    RollupTarget,
    Rule,
    RuleSet,
)
from m3_tpu.msg import Consumer, ConsumerService, Producer, Topic, TopicService
from m3_tpu.testing.cluster import SettableClock

S = 1_000_000_000
TEN_S = StoragePolicy.of("10s", "2d")
ONE_M = StoragePolicy.of("1m", "40d")


def _await(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def one_instance_placement(endpoint, num_shards=4):
    return initial_placement(
        [Instance(id="c0", endpoint=endpoint)], num_shards=num_shards,
        replica_factor=1)


class TestTopicService:
    def test_crud_and_watch(self):
        store = cluster_kv.MemStore()
        svc = TopicService(store)
        t = Topic("aggregated_metrics", 4).add_consumer(
            ConsumerService("coordinator"))
        svc.upsert(t)
        got = svc.get("aggregated_metrics")
        assert got.num_shards == 4
        assert got.consumer_services[0].service_id == "coordinator"
        seen = []
        svc.on_change("aggregated_metrics", lambda topic: seen.append(topic))
        svc.upsert(got.remove_consumer("coordinator"))
        assert seen and not seen[-1].consumer_services


class TestProducerConsumer:
    def test_publish_consume_ack(self):
        received = []
        consumer = Consumer(lambda shard, value: received.append((shard, value))).start()
        try:
            topic = Topic("t", 4, (ConsumerService("svc"),))
            p = one_instance_placement(consumer.endpoint)
            prod = Producer(topic, {"svc": lambda: p})
            for i in range(20):
                prod.publish(i % 4, b"payload-%d" % i)
            assert _await(lambda: len(received) == 20)
            assert _await(lambda: prod.unacked() == 0)
            # Ref-counted buffer drains once every consumer service acked.
            assert _await(lambda: prod.buffered_bytes() == 0)
            assert {v for _, v in received} == {b"payload-%d" % i for i in range(20)}
            prod.close()
        finally:
            consumer.close()

    def test_redelivery_after_consumer_restart(self):
        """Messages published while the consumer is down are redelivered by
        the retry pass once it returns (at-least-once, message_writer.go)."""
        received = []
        consumer = Consumer(lambda s, v: received.append(v)).start()
        endpoint = consumer.endpoint
        host, _, port = endpoint.rpartition(":")
        topic = Topic("t", 1, (ConsumerService("svc"),))
        p = one_instance_placement(endpoint, num_shards=1)
        prod = Producer(topic, {"svc": lambda: p}, retry_delay_s=0.05)
        try:
            prod.publish(0, b"before")
            assert _await(lambda: received == [b"before"])
            consumer.close()
            time.sleep(0.05)
            prod.publish(0, b"during")  # connection is dead -> send fails
            # Restart a consumer on the SAME port.
            consumer = Consumer(lambda s, v: received.append(v),
                                port=int(port)).start()
            for _ in range(100):
                prod.retry_unacked()
                if b"during" in received:
                    break
                time.sleep(0.05)
            assert b"during" in received
            assert _await(lambda: prod.unacked() == 0)
        finally:
            prod.close()
            consumer.close()

    def test_unrouted_messages_recover_when_placement_appears(self):
        """Regression: publishes during a placement gap must deliver once a
        placement exists (at-least-once across placement updates)."""
        received = []
        consumer = Consumer(lambda s, v: received.append(v)).start()
        placement = {"p": None}  # no placement yet
        topic = Topic("t", 1, (ConsumerService("svc"),))
        prod = Producer(topic, {"svc": lambda: placement["p"]},
                        retry_delay_s=0.05)
        try:
            prod.publish(0, b"early")
            assert prod.unacked() == 1
            time.sleep(0.1)
            assert received == []
            placement["p"] = one_instance_placement(consumer.endpoint, 1)
            for _ in range(100):
                prod.retry_unacked()
                if received:
                    break
                time.sleep(0.02)
            # at-least-once: the background retry pass may legitimately
            # resend before the first ack lands, so duplicates are valid
            assert received and set(received) == {b"early"}
            assert _await(lambda: prod.unacked() == 0)
        finally:
            prod.close()
            consumer.close()

    def test_partial_ack_batch_flushes_on_idle(self):
        """Regression: ack_batch larger than in-flight count must still ack
        via the idle flush."""
        received = []
        consumer = Consumer(lambda s, v: received.append(v), ack_batch=10).start()
        topic = Topic("t", 1, (ConsumerService("svc"),))
        p = one_instance_placement(consumer.endpoint, 1)
        prod = Producer(topic, {"svc": lambda: p})
        try:
            for i in range(3):
                prod.publish(0, b"m%d" % i)
            assert _await(lambda: len(received) == 3)
            assert _await(lambda: prod.unacked() == 0, timeout=3.0)
        finally:
            prod.close()
            consumer.close()

    def test_publish_backpressure_before_drop_oldest(self):
        # No consumer reachable: the high watermark surfaces typed
        # Backpressure to publish() BEFORE any data loss — the buffer
        # stays bounded and nothing is silently dropped.
        from m3_tpu.utils.limits import Backpressure

        topic = Topic("t", 1, (ConsumerService("svc"),))
        dead = one_instance_placement("127.0.0.1:1", num_shards=1)
        prod = Producer(topic, {"svc": lambda: dead}, max_buffer_bytes=1000)
        with pytest.raises(Backpressure):
            for i in range(50):
                prod.publish(0, b"x" * 100)
        assert prod.buffered_bytes() <= 1000
        assert prod.backpressure_rejections >= 1
        assert prod.dropped_oldest == 0  # bounded WITHOUT silent loss
        prod.close()

    def test_drop_oldest_bounds_buffer(self):
        # high_watermark > 1 opts out of the backpressure gate: the
        # reference's pure drop-oldest semantics — cap forces drops.
        topic = Topic("t", 1, (ConsumerService("svc"),))
        dead = one_instance_placement("127.0.0.1:1", num_shards=1)
        prod = Producer(topic, {"svc": lambda: dead}, max_buffer_bytes=1000,
                        high_watermark=2.0)
        for i in range(50):
            prod.publish(0, b"x" * 100)
        assert prod.buffered_bytes() <= 1000
        assert prod.dropped_oldest >= 40
        prod.close()


class TestMatcher:
    def _publish_rules(self, store, policies=(TEN_S,), version=1):
        rs = RuleSet(
            b"default", version,
            mapping_rules=[Rule([MappingRuleSnapshot(
                "api-metrics", 0, TagsFilter({"service": "api"}),
                0, tuple(policies))])],
            rollup_rules=[Rule([RollupRuleSnapshot(
                "per-region", 0, TagsFilter({"service": "api"}),
                (RollupTarget(
                    Pipeline((Op.roll(b"api_by_region", (b"region",),
                                      magg.AggID.compress([magg.AggType.SUM])),)),
                    (ONE_M,)),))])],
        )
        RuleSetStore(store).publish(rs)
        return rs

    def test_match_and_cache(self):
        store = cluster_kv.MemStore()
        clock = SettableClock(100 * S)
        self._publish_rules(store)
        m = Matcher(RuleSetStore(store), b"default", clock=clock)
        mid = metric_id.encode(b"requests", {b"service": b"api", b"region": b"us"})
        r1 = m.match(mid)
        assert r1 is not None
        policies = r1.for_existing_id[0].metadata.pipelines[0].storage_policies
        assert policies == (TEN_S,)
        assert len(r1.for_new_rollup_ids) == 1
        rid = r1.for_new_rollup_ids[0].id
        assert b"api_by_region" in rid and b"region" in rid
        m.match(mid)
        assert m.hits == 1 and m.misses == 1

    def test_rules_update_invalidates_cache(self):
        store = cluster_kv.MemStore()
        clock = SettableClock(100 * S)
        self._publish_rules(store)
        rstore = RuleSetStore(store)
        m = Matcher(rstore, b"default", clock=clock)
        mid = metric_id.encode(b"requests", {b"service": b"api"})
        r1 = m.match(mid)
        self._publish_rules(store, policies=(TEN_S, ONE_M), version=2)
        r2 = m.match(mid)
        assert r2.for_existing_id[0].metadata.pipelines[0].storage_policies == (
            TEN_S, ONE_M)

    def test_multi_op_pipeline_roundtrips_through_kv(self):
        """Regression: rollup targets with transform+rollup pipelines must
        survive KV serialization intact."""
        from m3_tpu.metrics.matcher import ruleset_from_json, ruleset_to_json
        from m3_tpu.metrics.transformation import TransformType

        pipe = Pipeline((
            Op.transform(TransformType.PERSECOND),
            Op.roll(b"rolled", (b"region",), magg.AggID.compress([magg.AggType.SUM])),
        ))
        rs = RuleSet(
            b"ns", 3,
            rollup_rules=[Rule([RollupRuleSnapshot(
                "r", 0, TagsFilter({"a": "b"}),
                (RollupTarget(pipe, (TEN_S,)),))])])
        back = ruleset_from_json(ruleset_to_json(rs))
        target = back.rollup_rules[0].snapshots[0].targets[0]
        assert target.pipeline == pipe
        assert target.storage_policies == (TEN_S,)

    def test_no_match_gives_empty_metadata(self):
        store = cluster_kv.MemStore()
        clock = SettableClock(100 * S)
        self._publish_rules(store)
        m = Matcher(RuleSetStore(store), b"default", clock=clock)
        mid = metric_id.encode(b"other", {b"service": b"web"})
        r = m.match(mid)
        assert r.for_existing_id[0].metadata.pipelines == ()


class TestProducerHandler:
    def test_flush_rides_m3msg_to_consumer(self):
        """aggregator flush -> ProducerHandler -> m3msg TCP -> consumer
        decode (the §3.4 handler.Handle -> m3msg -> coordinator hop)."""
        from m3_tpu.aggregator import ProducerHandler, decode_aggregated_batch
        from m3_tpu.metrics.metadata import Metadata, PipelineMetadata, StagedMetadata
        from m3_tpu.metrics.metric import MetricUnion

        received = []
        consumer = Consumer(
            lambda shard, value: received.extend(decode_aggregated_batch(value))).start()
        try:
            topic = Topic("aggregated_metrics", 4, (ConsumerService("coord"),))
            p = one_instance_placement(consumer.endpoint)
            prod = Producer(topic, {"coord": lambda: p})
            clock = SettableClock(100 * S)
            agg = Aggregator(num_shards=8, clock=clock,
                             flush_handler=ProducerHandler(prod, 4))
            md = (StagedMetadata(0, False, Metadata((PipelineMetadata(0, (TEN_S,)),))),)
            agg.add_untimed(MetricUnion.counter(b"total_requests", 41), md)
            agg.add_untimed(MetricUnion.counter(b"total_requests", 1), md)
            clock.advance(10 * S)
            agg.flush()
            assert _await(lambda: len(received) == 1)
            m = received[0]
            assert m.id == b"total_requests"
            assert m.value == 42.0
            assert m.time_nanos == 110 * S
            assert m.storage_policy == TEN_S
            prod.close()
        finally:
            consumer.close()


class TestCollectorEndToEnd:
    def test_report_through_aggregator(self):
        """collector Reporter -> matcher -> aggregator client -> aggregator
        -> flush handler, including the rollup ID emitted by the rollup rule
        (the §3.4 ingest->flush pipeline, minus the network)."""
        store = cluster_kv.MemStore()
        clock = SettableClock(600 * S)
        rs = RuleSet(
            b"default", 1,
            mapping_rules=[Rule([MappingRuleSnapshot(
                "all", 0, TagsFilter({"service": "api"}), 0, (TEN_S,))])],
            rollup_rules=[Rule([RollupRuleSnapshot(
                "sum-by-region", 0, TagsFilter({"service": "api"}),
                (RollupTarget(
                    Pipeline((Op.roll(b"api_region_total", (b"region",),
                                      magg.AggID.compress([magg.AggType.SUM])),)),
                    (TEN_S,)),))])],
        )
        rstore = RuleSetStore(store)
        rstore.publish(rs)
        matcher = Matcher(rstore, b"default", clock=clock)

        cap = CaptureHandler()
        agg = Aggregator(num_shards=16, clock=clock, flush_handler=cap)
        p = initial_placement([Instance(id="agg0", endpoint="l:0")],
                              num_shards=16, replica_factor=1)
        client = AggregatorClient(16, lambda: p, {"agg0": agg.add_untimed})
        rep = Reporter(matcher, client)

        for host, v in [(b"a", 5), (b"b", 7)]:
            mid = metric_id.encode(
                b"requests", {b"service": b"api", b"region": b"us", b"host": host})
            assert rep.report_counter(mid, v)
        clock.advance(10 * S)
        agg.flush()
        assert rep.reported == 2
        # Each original ID emitted its own sum...
        originals = [m for m in cap.metrics if b"host=" in m.id]
        assert sorted(m.value for m in originals) == [5.0, 7.0]
        # ...and both contributed to one rolled-up series keyed by region.
        rollups = [m for m in cap.metrics if m.id.startswith(b"api_region_total")]
        assert len(rollups) == 1
        assert rollups[0].value == 12.0


    def test_handler_failure_redelivered_not_fatal(self):
        """A RAISING consumer handler is an application error, not stream
        desync: the message goes unacked (redelivered by the producer's
        own retry loop — no manual retry_unacked pumping), later messages
        keep flowing, and the connection survives. Reference:
        writer/message_writer.go scanMessageQueue's scheduled retry."""
        import sys

        seen = {}
        lock = threading.Lock()

        def handler(shard, value):
            with lock:
                seen[value] = seen.get(value, 0) + 1
                n = seen[value]
            if value == b"poison" and n == 1:
                raise ValueError("injected handler failure")

        consumer = Consumer(handler).start()
        topic = Topic("t", 2, (ConsumerService("svc"),))
        p = one_instance_placement(consumer.endpoint)
        prod = Producer(topic, {"svc": lambda: p}, retry_delay_s=0.05)
        try:
            prod.publish(0, b"ok-1")
            prod.publish(1, b"poison")
            prod.publish(0, b"ok-2")
            assert _await(lambda: seen.get(b"poison", 0) >= 2, timeout=10)
            assert _await(lambda: prod.unacked() == 0, timeout=10)
            assert seen.get(b"ok-1") and seen.get(b"ok-2")
        finally:
            prod.close()
            consumer.close()
