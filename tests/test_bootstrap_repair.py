"""Bootstrap chain + repair tests (reference test model:
src/dbnode/integration peers_bootstrap_*.go, fs_bootstrap tests,
storage/repair tests)."""

import time

import numpy as np
import pytest

from m3_tpu.client import Session, SessionOptions
from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.persist.commitlog import CommitLog
from m3_tpu.persist.fs import PersistManager
from m3_tpu.storage.bootstrap import (
    BootstrapContext,
    BootstrapProcess,
    apply_peer_tiles,
    apply_peer_tiles_ref,
)
from m3_tpu.storage.block import encode_block
from m3_tpu.storage.database import Database
from m3_tpu.storage.namespace import NamespaceOptions
from m3_tpu.storage.repair import DatabaseRepairer, RepairOptions, ShardRepairer
from m3_tpu.storage.shard import Shard, ShardOptions
from m3_tpu.storage.timerange import ShardTimeRanges, intersect, normalize, subtract
from m3_tpu.testing import ClusterHarness, FaultPlan, FaultProxy
from m3_tpu.utils import xtime
from m3_tpu.utils.retry import RetryOptions

NS = b"default"
T0 = 1_600_000_000_000_000_000


def test_timerange_algebra():
    assert normalize([(5, 10), (0, 6)]) == [(0, 10)]
    assert normalize([(0, 5), (5, 10)]) == [(0, 10)]
    assert subtract([(0, 10)], [(3, 5)]) == [(0, 3), (5, 10)]
    assert subtract([(0, 10)], [(0, 10)]) == []
    assert subtract([(0, 4), (6, 10)], [(2, 8)]) == [(0, 2), (8, 10)]
    assert intersect([(0, 10)], [(5, 15)]) == [(5, 10)]
    str_ = ShardTimeRanges.uniform([1, 2], 0, 100)
    rem = str_.subtract(ShardTimeRanges({1: [(0, 100)], 2: [(0, 40)]}))
    assert rem.m == {2: [(40, 100)]}
    assert not rem.is_empty() and rem.total_ns() == 60


def _mk_db(tmp, with_cl=False, num_shards=8):
    cl = CommitLog(str(tmp / "commitlog")) if with_cl else None
    db = Database(ShardSet(num_shards), commitlog=cl, clock=lambda: _mk_db.now)
    db.create_namespace(NS, NamespaceOptions(index_enabled=False))
    return db


_mk_db.now = T0


def test_fs_then_commitlog_chain(tmp_path):
    _mk_db.now = T0
    db = _mk_db(tmp_path, with_cl=True)
    pm = PersistManager(str(tmp_path / "data"))
    # Old block (will be sealed + flushed) ...
    old_ts = [T0 - i * xtime.SECOND for i in range(1, 11)]
    db.write_batch(NS, [b"series.flushed"] * 10, old_ts, np.arange(10.0))
    # ... advance past block end so it seals, write fresh points (commitlog only)
    _mk_db.now = T0 + 2 * xtime.HOUR + 11 * xtime.MINUTE
    db.tick()
    assert db.flush(pm) >= 1
    fresh_ts = [_mk_db.now - i * xtime.SECOND for i in range(1, 6)]
    db.write_batch(NS, [b"series.walonly"] * 5, fresh_ts, np.arange(5.0) + 100)
    db.commitlog.flush()

    # A fresh db bootstraps: fs claims the flushed block, commitlog the rest.
    db2 = _mk_db(tmp_path / "node2")
    proc = BootstrapProcess(
        chain=("filesystem", "commitlog", "uninitialized_topology"),
        ctx=BootstrapContext(
            persist=pm, commitlog_dir=str(tmp_path / "commitlog"),
            shard_lookup=db2.shard_set.lookup),
    )
    results = proc.run(db2, now_ns=_mk_db.now)
    res = results[NS]
    assert res.unfulfilled.is_empty()
    assert not res.claimed["filesystem"].is_empty()
    assert db2.bootstrapped

    t, v = db2.read(NS, b"series.flushed", T0 - xtime.HOUR, T0 + xtime.HOUR)
    np.testing.assert_array_equal(v, np.arange(9.0, -1.0, -1))
    t, v = db2.read(NS, b"series.walonly", _mk_db.now - xtime.HOUR, _mk_db.now + 1)
    np.testing.assert_array_equal(v, np.array([104.0, 103, 102, 101, 100]))


@pytest.fixture(scope="module")
def cluster():
    h = ClusterHarness(n_nodes=3, replica_factor=3, num_shards=8,
                       ns_opts=NamespaceOptions())
    yield h
    h.close()


def _seed_and_seal(cluster, session, ids, base_val=0.0):
    now = cluster.clock.now_ns
    ts = [now - i * xtime.SECOND for i in range(12)]
    for j, sid in enumerate(ids):
        session.write_batch(NS, [sid] * 12, ts,
                            np.arange(12.0) + base_val + 10 * j,
                            [{b"role": b"seed"}] * 12)
    cluster.clock.advance(2 * xtime.HOUR + 11 * xtime.MINUTE)
    cluster.tick_all()
    return ts


def test_peers_bootstrap(cluster):
    session = Session(cluster.topology, SessionOptions(timeout_s=10))
    ids = [b"peer.a", b"peer.b", b"peer.c"]
    _seed_and_seal(cluster, session, ids)

    # Replacement node: empty db, same shard space, bootstraps from peers.
    newdb = Database(ShardSet(cluster.num_shards), clock=cluster.clock)
    newdb.create_namespace(NS, NamespaceOptions(index_enabled=False))
    proc = BootstrapProcess(
        chain=("peers", "uninitialized_topology"),
        ctx=BootstrapContext(session=session,
                             placement=cluster.placement_svc.get()),
    )
    res = proc.run(newdb)[NS]
    assert res.unfulfilled.is_empty()
    for j, sid in enumerate(ids):
        t, v = newdb.read(NS, sid, 0, cluster.clock.now_ns)
        assert len(t) == 12
        np.testing.assert_array_equal(np.sort(v), np.arange(12.0) + 10 * j)
    session.close()


def _assert_shards_bit_identical(sh_new: Shard, sh_ref: Shard):
    assert sh_new.registry.all_ids() == sh_ref.registry.all_ids()
    assert sorted(sh_new.blocks) == sorted(sh_ref.blocks)
    for bs, blk in sh_new.blocks.items():
        ref = sh_ref.blocks[bs]
        np.testing.assert_array_equal(blk.series_indices, ref.series_indices)
        np.testing.assert_array_equal(blk.words, ref.words)
        np.testing.assert_array_equal(blk.nbits, ref.nbits)
        np.testing.assert_array_equal(blk.npoints, ref.npoints)
        assert blk.window == ref.window and blk.time_unit == ref.time_unit


def _tile_from_block(blk, ids):
    return {"ids": ids, "words": blk.words, "nbits": blk.nbits,
            "npoints": blk.npoints, "window": int(blk.window),
            "time_unit": int(blk.time_unit)}


def _random_tile(rng, bs, n_series, prefix, nanos=False):
    """Encode a random tile the way a peer block would arrive: real
    encode path, optional sub-second timestamps (NANOSECOND unit) to
    exercise the mixed-unit merge."""
    npts = rng.integers(1, 5, n_series).astype(np.int32)
    w = int(npts.max())
    ts = np.zeros((n_series, w), np.int64)
    vs = rng.standard_normal((n_series, w))
    for i in range(n_series):
        step = xtime.SECOND if not nanos else xtime.SECOND + 7
        pts = bs + np.arange(w, dtype=np.int64) * step + i * xtime.SECOND
        ts[i] = pts
        ts[i, npts[i]:] = pts[npts[i] - 1]
        vs[i, npts[i]:] = vs[i, npts[i] - 1]
    blk = encode_block(bs, np.arange(n_series, dtype=np.int32), ts, vs, npts)
    ids = [b"%s-%04d" % (prefix, i) for i in range(n_series)]
    return _tile_from_block(blk, ids)


def test_batched_apply_matches_per_row_oracle_synthetic():
    """Property: apply_peer_tiles (batched registry + columnar install)
    is bit-identical to the retained per-row oracle across seeded tile
    maps — multiple blocks, multiple tiles per block (split holders),
    shared series across blocks, tags, and mixed time units."""
    for seed in range(8):
        rng = np.random.default_rng(seed)
        tiles = {}
        tags = {}
        n_blocks = int(rng.integers(1, 4))
        for b in range(n_blocks):
            bs = T0 + b * 2 * xtime.HOUR
            tlist = []
            n_tiles = int(rng.integers(1, 3))
            for t in range(n_tiles):
                prefix = b"s%d" % t if rng.random() < 0.5 else b"s0"
                tile = _random_tile(rng, bs, int(rng.integers(1, 9)),
                                    prefix, nanos=(seed % 4 == 3 and t == 1))
                tlist.append(tile)
                for sid in tile["ids"]:
                    if rng.random() < 0.5:
                        tags.setdefault(sid, {b"case": b"%d" % seed})
            # distinct sids per (bs): dedupe tile ids across the block
            seen = set()
            for tile in tlist:
                keep = [i for i, sid in enumerate(tile["ids"])
                        if sid not in seen and not seen.add(sid)]
                tile["ids"] = [tile["ids"][i] for i in keep]
                tile["words"] = np.asarray(tile["words"])[keep]
                tile["nbits"] = np.asarray(tile["nbits"])[keep]
                tile["npoints"] = np.asarray(tile["npoints"])[keep]
            tiles[bs] = [t for t in tlist if len(t["ids"])]
        opts = ShardOptions()
        sh_new, sh_ref = Shard(0, opts), Shard(0, opts)
        n_new = apply_peer_tiles(sh_new, tiles, tags)
        n_ref = apply_peer_tiles_ref(sh_ref, tiles, tags)
        assert n_new == n_ref
        _assert_shards_bit_identical(sh_new, sh_ref)
        for sid, tg in tags.items():
            idx = sh_new.registry.get(sid)
            assert sh_new.registry.tags_of(idx) == tg
            assert sh_ref.registry.tags_of(sh_ref.registry.get(sid)) == tg


def test_batched_apply_matches_per_row_oracle_cluster(cluster):
    """End-to-end oracle cases: seeded writes through the real session,
    tiles fetched over the real peer-streaming RPC, both apply paths
    asserted bit-identical per shard (the bench runs the same check on
    its 100k-series migration)."""
    session = Session(cluster.topology, SessionOptions(timeout_s=10))
    for seed in range(4):
        rng = np.random.default_rng(100 + seed)
        ids = [b"oracle%d.%03d" % (seed, i) for i in range(24)]
        _seed_and_seal(cluster, session, ids, base_val=float(seed) * 100)
        shard_ids = sorted({cluster.nodes["node0"].db.shard_set.lookup(s)
                            for s in ids})
        checked = 0
        for shard_id in shard_ids[:3]:
            exclude = rng.choice(["node0", "node1", "node2", None])
            tiles, tags, failed = session.fetch_block_tiles_from_peers(
                NS, int(shard_id), 0, cluster.clock.now_ns,
                exclude_host=None if exclude is None else str(exclude))
            assert not failed
            if not tiles:
                continue
            opts = ShardOptions()
            sh_new, sh_ref = Shard(0, opts), Shard(0, opts)
            apply_peer_tiles(sh_new, tiles, tags)
            apply_peer_tiles_ref(sh_ref, tiles, tags)
            _assert_shards_bit_identical(sh_new, sh_ref)
            checked += 1
        assert checked > 0
    session.close()


def test_mid_stream_peer_death_replans_to_next_holder(cluster):
    """A dead holder ranked first in the plan must fail over to the next
    checksum holder instead of dropping the block (the wave-based
    fetch_block_tiles fallback), and typed errors must be surfaced."""
    session = Session(cluster.topology, SessionOptions(
        timeout_s=10, retry=RetryOptions(max_attempts=1)))
    ids = [b"replan.a", b"replan.b"]
    _seed_and_seal(cluster, session, ids)
    shard_id = cluster.nodes["node0"].db.shard_set.lookup(ids[0])
    meta = session.fetch_blocks_metadata_from_peers(
        NS, shard_id, 0, cluster.clock.now_ns)
    live = sorted(h for h in meta if meta[h].get(ids[0]))
    assert len(live) >= 2
    dead, backup = live[0], live[1]
    # Kill the primary AFTER metadata: the fetch wave must re-plan. A
    # fresh session forces real (re)connects — the stopped listener
    # refuses them (established handler threads would otherwise keep
    # serving the old session's pooled sockets).
    cluster.stop_node(dead)
    session2 = Session(cluster.topology, SessionOptions(
        timeout_s=10, retry=RetryOptions(max_attempts=1)))
    try:
        shard_ids = [s for s in ids
                     if cluster.nodes["node0"].db.shard_set.lookup(s)
                     == shard_id]
        keys = [(sid, b["bs"]) for sid in shard_ids
                for b in meta[backup][sid]["blocks"]]
        holders = {k: [dead, backup] for k in keys}
        errors = {}
        tiles, failed = session2.fetch_block_tiles(
            NS, shard_id, holders, errors=errors)
        assert not failed, failed
        assert dead in errors  # typed, surfaced — not silently skipped
        got = {(sid, bs) for bs, tlist in tiles.items()
               for t in tlist for sid in t["ids"]}
        assert got == set(keys)
    finally:
        # Restart the dead node so the module-scoped cluster stays
        # 3/3 for the remaining tests.
        from m3_tpu.rpc import NodeServer, NodeService

        node = cluster.nodes[dead]
        node.server = NodeServer(NodeService(node.db)).start()
        p = cluster.placement_svc.get()
        p.instances[dead].endpoint = node.endpoint
        cluster.placement_svc._put(p, p.version)
        session.close()
        session2.close()


def test_deadline_bounded_bootstrap_against_delayed_peer():
    """A faultnet-delayed peer must bound the peers bootstrap at the
    configured budget and surface partial coverage (unfulfilled ranges),
    not stall the whole chain."""
    from m3_tpu.cluster.placement import Instance, initial_placement
    from m3_tpu.cluster.topology import StaticTopology
    from m3_tpu.rpc import NodeServer, NodeService

    db = Database(ShardSet(2), clock=lambda: T0)
    db.create_namespace(NS, NamespaceOptions(index_enabled=False))
    now = {"t": T0}
    db.clock = lambda: now["t"]
    ids = [b"slow.%02d" % i for i in range(8)]
    db.write_batch(NS, ids, np.full(len(ids), T0, np.int64),
                   np.arange(8.0))
    now["t"] = T0 + 2 * xtime.HOUR + 11 * xtime.MINUTE
    db.tick()
    db.mark_bootstrapped()
    srv = NodeServer(NodeService(db)).start()
    # Every frame in BOTH directions held 0.4s: a full metadata+tile
    # exchange costs far more than the 0.6s budget.
    proxy = FaultProxy(srv.endpoint,
                       FaultPlan(seed=3, delay=1.0, delay_s=0.4)).start()
    placement = initial_placement(
        [Instance(id="donor", endpoint=proxy.endpoint)], 2, 1)
    session = Session(StaticTopology(placement), SessionOptions(
        timeout_s=30, retry=RetryOptions(max_attempts=1)))
    fresh = Database(ShardSet(2), clock=lambda: now["t"])
    fresh.create_namespace(NS, NamespaceOptions(index_enabled=False))
    proc = BootstrapProcess(
        chain=("peers",),
        ctx=BootstrapContext(session=session, placement=placement,
                             host_id="joiner", peer_deadline_s=0.6))
    t0 = time.monotonic()
    res = proc.run(fresh, now_ns=now["t"])[NS]
    elapsed = time.monotonic() - t0
    # Two shards, each bounded by its own 0.6s budget (+ slack for the
    # delayed in-flight frame): nowhere near the unbounded many-page
    # exchange, and the hole is SURFACED as unfulfilled ranges.
    assert elapsed < 5.0, f"bootstrap not deadline-bounded: {elapsed:.1f}s"
    assert not res.unfulfilled.is_empty()
    session.close()
    proxy.close()
    srv.close()


def test_repairer_scheduling_jitter_and_backoff():
    """dbRepairer cadence: seeded jitter within [interval, interval*(1+f)),
    failure backoff stretches the next delay, success resets it."""
    db = Database(ShardSet(2), clock=lambda: T0)
    rep = DatabaseRepairer(
        db, session=None,
        opts=RepairOptions(interval_s=10.0, jitter_frac=0.5, seed=11))
    delays = [rep.next_delay_s() for _ in range(50)]
    assert all(10.0 <= d < 15.0 for d in delays)
    # deterministic under the seed
    rep2 = DatabaseRepairer(
        db, session=None,
        opts=RepairOptions(interval_s=10.0, jitter_frac=0.5, seed=11))
    assert [rep2.next_delay_s() for _ in range(50)] == delays
    rep.consecutive_failures = 3
    assert rep.next_delay_s() > 10.0 + rep._backoff.backoff_for(3) - 1e-9
    rep.consecutive_failures = 0
    assert rep.next_delay_s() < 15.0


def test_repair_detects_and_heals_divergence(cluster):
    session = Session(cluster.topology, SessionOptions(timeout_s=10))
    ids = [b"repair.x", b"repair.y"]
    _seed_and_seal(cluster, session, ids, base_val=500.0)

    # Damage node0: drop one sealed block containing repair.x.
    node0 = cluster.nodes["node0"]
    shard_id = node0.db.shard_set.lookup(b"repair.x")
    shard = node0.db.namespace(NS).shards[shard_id]
    victim_bs = None
    idx = shard.registry.get(b"repair.x")
    for bs, blk in list(shard.blocks.items()):
        if blk.row_of(idx) is not None:
            victim_bs = bs
            del shard.blocks[bs]
            break
    assert victim_bs is not None

    rep = ShardRepairer(session, host_id="node0")
    stats = rep.repair_shard(node0.db.namespace(NS), shard_id,
                             0, cluster.clock.now_ns)
    assert stats.rows_missing_locally >= 1
    assert stats.blocks_rebuilt >= 1
    assert victim_bs in shard.blocks
    t, v = shard.read(b"repair.x", 0, cluster.clock.now_ns)
    assert len(t) >= 12
    session.close()
