"""Bootstrap chain + repair tests (reference test model:
src/dbnode/integration peers_bootstrap_*.go, fs_bootstrap tests,
storage/repair tests)."""

import numpy as np
import pytest

from m3_tpu.client import Session, SessionOptions
from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.persist.commitlog import CommitLog
from m3_tpu.persist.fs import PersistManager
from m3_tpu.storage.bootstrap import (
    BootstrapContext,
    BootstrapProcess,
)
from m3_tpu.storage.database import Database
from m3_tpu.storage.namespace import NamespaceOptions
from m3_tpu.storage.repair import ShardRepairer
from m3_tpu.storage.timerange import ShardTimeRanges, intersect, normalize, subtract
from m3_tpu.testing import ClusterHarness
from m3_tpu.utils import xtime

NS = b"default"
T0 = 1_600_000_000_000_000_000


def test_timerange_algebra():
    assert normalize([(5, 10), (0, 6)]) == [(0, 10)]
    assert normalize([(0, 5), (5, 10)]) == [(0, 10)]
    assert subtract([(0, 10)], [(3, 5)]) == [(0, 3), (5, 10)]
    assert subtract([(0, 10)], [(0, 10)]) == []
    assert subtract([(0, 4), (6, 10)], [(2, 8)]) == [(0, 2), (8, 10)]
    assert intersect([(0, 10)], [(5, 15)]) == [(5, 10)]
    str_ = ShardTimeRanges.uniform([1, 2], 0, 100)
    rem = str_.subtract(ShardTimeRanges({1: [(0, 100)], 2: [(0, 40)]}))
    assert rem.m == {2: [(40, 100)]}
    assert not rem.is_empty() and rem.total_ns() == 60


def _mk_db(tmp, with_cl=False, num_shards=8):
    cl = CommitLog(str(tmp / "commitlog")) if with_cl else None
    db = Database(ShardSet(num_shards), commitlog=cl, clock=lambda: _mk_db.now)
    db.create_namespace(NS, NamespaceOptions(index_enabled=False))
    return db


_mk_db.now = T0


def test_fs_then_commitlog_chain(tmp_path):
    _mk_db.now = T0
    db = _mk_db(tmp_path, with_cl=True)
    pm = PersistManager(str(tmp_path / "data"))
    # Old block (will be sealed + flushed) ...
    old_ts = [T0 - i * xtime.SECOND for i in range(1, 11)]
    db.write_batch(NS, [b"series.flushed"] * 10, old_ts, np.arange(10.0))
    # ... advance past block end so it seals, write fresh points (commitlog only)
    _mk_db.now = T0 + 2 * xtime.HOUR + 11 * xtime.MINUTE
    db.tick()
    assert db.flush(pm) >= 1
    fresh_ts = [_mk_db.now - i * xtime.SECOND for i in range(1, 6)]
    db.write_batch(NS, [b"series.walonly"] * 5, fresh_ts, np.arange(5.0) + 100)
    db.commitlog.flush()

    # A fresh db bootstraps: fs claims the flushed block, commitlog the rest.
    db2 = _mk_db(tmp_path / "node2")
    proc = BootstrapProcess(
        chain=("filesystem", "commitlog", "uninitialized_topology"),
        ctx=BootstrapContext(
            persist=pm, commitlog_dir=str(tmp_path / "commitlog"),
            shard_lookup=db2.shard_set.lookup),
    )
    results = proc.run(db2, now_ns=_mk_db.now)
    res = results[NS]
    assert res.unfulfilled.is_empty()
    assert not res.claimed["filesystem"].is_empty()
    assert db2.bootstrapped

    t, v = db2.read(NS, b"series.flushed", T0 - xtime.HOUR, T0 + xtime.HOUR)
    np.testing.assert_array_equal(v, np.arange(9.0, -1.0, -1))
    t, v = db2.read(NS, b"series.walonly", _mk_db.now - xtime.HOUR, _mk_db.now + 1)
    np.testing.assert_array_equal(v, np.array([104.0, 103, 102, 101, 100]))


@pytest.fixture(scope="module")
def cluster():
    h = ClusterHarness(n_nodes=3, replica_factor=3, num_shards=8,
                       ns_opts=NamespaceOptions())
    yield h
    h.close()


def _seed_and_seal(cluster, session, ids, base_val=0.0):
    now = cluster.clock.now_ns
    ts = [now - i * xtime.SECOND for i in range(12)]
    for j, sid in enumerate(ids):
        session.write_batch(NS, [sid] * 12, ts,
                            np.arange(12.0) + base_val + 10 * j,
                            [{b"role": b"seed"}] * 12)
    cluster.clock.advance(2 * xtime.HOUR + 11 * xtime.MINUTE)
    cluster.tick_all()
    return ts


def test_peers_bootstrap(cluster):
    session = Session(cluster.topology, SessionOptions(timeout_s=10))
    ids = [b"peer.a", b"peer.b", b"peer.c"]
    _seed_and_seal(cluster, session, ids)

    # Replacement node: empty db, same shard space, bootstraps from peers.
    newdb = Database(ShardSet(cluster.num_shards), clock=cluster.clock)
    newdb.create_namespace(NS, NamespaceOptions(index_enabled=False))
    proc = BootstrapProcess(
        chain=("peers", "uninitialized_topology"),
        ctx=BootstrapContext(session=session,
                             placement=cluster.placement_svc.get()),
    )
    res = proc.run(newdb)[NS]
    assert res.unfulfilled.is_empty()
    for j, sid in enumerate(ids):
        t, v = newdb.read(NS, sid, 0, cluster.clock.now_ns)
        assert len(t) == 12
        np.testing.assert_array_equal(np.sort(v), np.arange(12.0) + 10 * j)
    session.close()


def test_repair_detects_and_heals_divergence(cluster):
    session = Session(cluster.topology, SessionOptions(timeout_s=10))
    ids = [b"repair.x", b"repair.y"]
    _seed_and_seal(cluster, session, ids, base_val=500.0)

    # Damage node0: drop one sealed block containing repair.x.
    node0 = cluster.nodes["node0"]
    shard_id = node0.db.shard_set.lookup(b"repair.x")
    shard = node0.db.namespace(NS).shards[shard_id]
    victim_bs = None
    idx = shard.registry.get(b"repair.x")
    for bs, blk in list(shard.blocks.items()):
        if blk.row_of(idx) is not None:
            victim_bs = bs
            del shard.blocks[bs]
            break
    assert victim_bs is not None

    rep = ShardRepairer(session, host_id="node0")
    stats = rep.repair_shard(node0.db.namespace(NS), shard_id,
                             0, cluster.clock.now_ns)
    assert stats.rows_missing_locally >= 1
    assert stats.blocks_rebuilt >= 1
    assert victim_bs in shard.blocks
    t, v = shard.read(b"repair.x", 0, cluster.clock.now_ns)
    assert len(t) >= 12
    session.close()
