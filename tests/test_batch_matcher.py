"""Property suite for the compiled batch matcher (ISSUE 20): seeded
(rule set x metric batch) corpora asserting the batch path EQUAL to the
per-metric oracle — filter translation, DROP_MUST classes, rollup id
generation, snapshot cutovers/tombstones, and rule-set version churn
mid-stream through the memoizing Matcher."""

import random

import pytest

from m3_tpu.cluster import kv as cluster_kv
from m3_tpu.coordinator.downsample import Downsampler
from m3_tpu.metrics import aggregation as magg
from m3_tpu.metrics import id as metric_id
from m3_tpu.metrics.batch_matcher import (
    CompiledRuleSet,
    filter_to_query,
    match_batch,
)
from m3_tpu.metrics.filters import TagsFilter
from m3_tpu.metrics.matcher import Matcher, RuleSetStore
from m3_tpu.metrics.metric import MetricType
from m3_tpu.metrics.pipeline import Op, Pipeline
from m3_tpu.metrics.policy import DropPolicy, StoragePolicy
from m3_tpu.metrics.rules import (
    MappingRuleSnapshot,
    RollupRuleSnapshot,
    RollupTarget,
    Rule,
    RuleSet,
)

S = 1_000_000_000
T0 = 1_700_000_000 * S

_POL = [
    (StoragePolicy.parse("10s:2d"),),
    (StoragePolicy.parse("1m:40d"),),
    (StoragePolicy.parse("10s:2d"), StoragePolicy.parse("1m:40d")),
]
_NAME_PATTERNS = ["svc*", "svc?_lat", "web_requests", "db_*", "*_lat",
                  "drop_*", "nomatch_zzz"]
_TAG_PATTERNS = [("dc", "east"), ("dc", "e*"), ("dc", "!west"),
                 ("host", "h?"), ("env", "prod"), ("env", "!*stage*")]
_AGG = [0, magg.AggID.compress([magg.AggType.MAX]),
        magg.AggID.compress([magg.AggType.SUM, magg.AggType.COUNT])]


def _rand_filter(rng) -> TagsFilter:
    filt = {"__name__": rng.choice(_NAME_PATTERNS)}
    for key, pat in rng.sample(_TAG_PATTERNS, rng.randrange(0, 3)):
        filt[key] = pat
    return TagsFilter(filt)


def _rand_ruleset(rng, version=1, n_mapping=12, n_rollup=6,
                  first_op_rollups_only=False) -> RuleSet:
    mapping = []
    for k in range(n_mapping):
        snaps = []
        # 1-3 snapshots with ascending cutovers; later ones may be in the
        # future (inactive at T0) or tombstoned
        cutovers = sorted(rng.sample(
            [0, T0 - 1000 * S, T0 - 10 * S, T0 + 50 * S, T0 + 500 * S],
            rng.randrange(1, 4)))
        for c in cutovers:
            snaps.append(MappingRuleSnapshot(
                f"map-{version}-{k}-{c}", c, _rand_filter(rng),
                rng.choice(_AGG), rng.choice(_POL),
                DropPolicy.DROP_MUST if rng.random() < 0.15
                else DropPolicy.NONE,
                rng.random() < 0.1))
        mapping.append(Rule(snaps))
    rollup = []
    for k in range(n_rollup):
        targets = []
        for j in range(rng.randrange(1, 3)):
            rop = Op.roll(b"rolled_%d_%d" % (k, j),
                          (b"dc",) if rng.random() < 0.5 else (b"dc", b"env"),
                          magg.AggID.compress([magg.AggType.SUM]))
            if first_op_rollups_only or rng.random() < 0.8:
                pipe = Pipeline((rop,))  # first-op rollup: new id
            else:
                # rollup not first: aggregates under the existing id
                # (matcher-level only — the aggregator tier executes
                # just first-op rollup pipelines)
                pipe = Pipeline((Op.aggregate(magg.AggType.MAX), rop))
            targets.append(RollupTarget(pipe, rng.choice(_POL)))
        rollup.append(Rule([RollupRuleSnapshot(
            f"roll-{version}-{k}", rng.choice([0, T0 - 5 * S]),
            _rand_filter(rng), tuple(targets), rng.random() < 0.1)]))
    return RuleSet(b"default", version, mapping, rollup)


def _rand_batch(rng, n):
    names = [b"svc1_lat", b"svc2_lat", b"svcX_cpu", b"web_requests",
             b"db_conns", b"db_errors", b"mem_lat", b"drop_me",
             b"unmatched_series"]
    out = []
    for _ in range(n):
        tags = {b"__name__": rng.choice(names)}
        if rng.random() < 0.8:
            tags[b"dc"] = rng.choice([b"east", b"west", b"eu"])
        if rng.random() < 0.6:
            tags[b"host"] = rng.choice([b"h1", b"h2", b"host9"])
        if rng.random() < 0.4:
            tags[b"env"] = rng.choice([b"prod", b"stage", b"prestaged"])
        out.append(tags)
    return out


def _encode(tags):
    return metric_id.encode(
        tags.get(b"__name__", b""),
        {k: v for k, v in tags.items() if k != b"__name__"})


@pytest.mark.parametrize("seed", range(8))
def test_match_batch_equals_forward_match_oracle(seed):
    rng = random.Random(seed)
    rs = _rand_ruleset(rng)
    active = rs.active_set()
    mids = [_encode(t) for t in _rand_batch(rng, 300)]
    compiled = CompiledRuleSet(active, T0)
    got = match_batch(compiled, mids, T0)
    ref = [active.forward_match(mid, T0, T0 + 1) for mid in mids]
    assert got == ref
    # the corpus must actually exercise rollup-id generation and drops
    assert any(r.for_new_rollup_ids for r in ref)


def test_filter_to_query_absent_tag_semantics():
    # positive pattern on an absent tag fails; negated pattern succeeds
    rs = RuleSet(b"default", 1, [Rule([MappingRuleSnapshot(
        "neg", 0, TagsFilter({"__name__": "m", "dc": "!east"}),
        0, _POL[0])])])
    active = rs.active_set()
    mids = [_encode({b"__name__": b"m"}),
            _encode({b"__name__": b"m", b"dc": b"east"}),
            _encode({b"__name__": b"m", b"dc": b"west"})]
    got = match_batch(CompiledRuleSet(active, T0), mids, T0)
    ref = [active.forward_match(m, T0, T0 + 1) for m in mids]
    assert got == ref
    assert got[0].for_existing_id[0].metadata.pipelines  # absent: matches
    assert not got[1].for_existing_id[0].metadata.pipelines
    assert got[2].for_existing_id[0].metadata.pipelines


def _matcher_env(seed=0):
    rng = random.Random(seed)
    store = RuleSetStore(cluster_kv.MemStore())
    store.publish(_rand_ruleset(rng, version=1))
    now = {"t": T0}
    m = Matcher(store, b"default", clock=lambda: now["t"])
    return rng, store, now, m


@pytest.mark.parametrize("seed", range(4))
def test_matcher_match_batch_equals_match(seed):
    rng, _store, _now, m = _matcher_env(seed)
    batch = [_encode(t) for t in _rand_batch(rng, 200)]
    got = m.match_batch(batch)
    # fresh per-metric matcher over the same store state as the oracle
    _rng2, _s2, _n2, ref_m = _matcher_env(seed)
    ref = [ref_m.match(mid) for mid in batch]
    assert got == ref


def test_match_batch_warm_pass_is_all_hits():
    rng, _store, _now, m = _matcher_env(3)
    batch = [_encode(t) for t in _rand_batch(rng, 200)]
    m.match_batch(batch)
    h0, m0 = m.hits, m.misses
    again = m.match_batch(batch)
    assert m.hits == h0 + len(batch) and m.misses == m0  # 100% warm hits
    assert again == m.match_batch(batch)


def test_version_churn_mid_stream_invalidates_memo():
    rng, store, _now, m = _matcher_env(7)
    batch = [_encode(t) for t in _rand_batch(rng, 150)]
    first = m.match_batch(batch)
    assert all(r.version == 1 for r in first)
    # KV rule update mid-stream: different rules, bumped version
    rs2 = _rand_ruleset(random.Random(99), version=2)
    store.publish(rs2)
    second = m.match_batch(batch)
    active2 = rs2.active_set()
    assert second == [active2.forward_match(mid, T0, T0 + 1)
                      for mid in batch]
    assert all(r.version == 2 for r in second)
    # memoized (generation, id) entries from the dead generation are
    # unreachable: a fresh warm pass hits only generation-2 entries
    h0 = m.hits
    assert m.match_batch(batch) == second
    assert m.hits == h0 + len(batch)


def _downsampler_pair(seed):
    rng = random.Random(seed)
    store = RuleSetStore(cluster_kv.MemStore())
    store.publish(_rand_ruleset(rng, version=1, first_op_rollups_only=True))
    now = {"t": T0}
    clock = lambda: now["t"]  # noqa: E731
    sinks = ([], [])
    got = Downsampler(Matcher(store, b"default", clock=clock),
                      lambda *a: sinks[0].append(a), clock=clock)
    ref = Downsampler(Matcher(store, b"default", clock=clock),
                      lambda *a: sinks[1].append(a), clock=clock)
    return rng, store, now, got, ref, sinks


@pytest.mark.parametrize("seed", range(4))
def test_downsampler_batch_equals_ref(seed):
    rng, _store, now, got, ref, sinks = _downsampler_pair(seed)
    types = [MetricType.GAUGE, MetricType.COUNTER, MetricType.TIMER]
    batch = [(tags, T0, float(i % 13) + 0.25, types[i % 3])
             for i, tags in enumerate(_rand_batch(rng, 250))]
    got.write_batch(batch)
    for tags, t, v, mt in batch:
        ref.write_ref(tags, t, v, mt)
    assert (got.samples_matched, got.samples_dropped) == \
        (ref.samples_matched, ref.samples_dropped)
    now["t"] = T0 + 120 * S
    got.flush()
    ref.flush()
    assert sorted(sinks[0]) == sorted(sinks[1])
    assert sinks[0]  # corpus produced aggregated output


def test_downsampler_batch_drop_must():
    store = RuleSetStore(cluster_kv.MemStore())
    store.publish(RuleSet(b"default", 1, [
        Rule([MappingRuleSnapshot(
            "keep", 0, TagsFilter({"__name__": "keep_*"}), 0, _POL[0])]),
        Rule([MappingRuleSnapshot(
            "drop", 0, TagsFilter({"__name__": "drop_*"}), 0, _POL[0],
            DropPolicy.DROP_MUST)]),
    ]))
    now = {"t": T0}
    sink = []
    ds = Downsampler(Matcher(store, b"default", clock=lambda: now["t"]),
                     lambda *a: sink.append(a), clock=lambda: now["t"])
    batch = [({b"__name__": b"keep_a"}, T0, 1.0, MetricType.GAUGE),
             ({b"__name__": b"drop_a"}, T0, 2.0, MetricType.GAUGE),
             ({b"__name__": b"drop_b"}, T0, 3.0, MetricType.GAUGE)]
    matched, dropped = ds.write_batch(batch)
    assert (matched, dropped) == (1, 2)
    now["t"] = T0 + 60 * S
    ds.flush()
    assert sink and all(b"keep_a" in row[0] for row in sink)
