"""Index segment persistence tests (reference: m3ninx/persist FST segment
files + the filesystem bootstrapper's index phase)."""

import numpy as np
import pytest

from m3_tpu.index import persist as idx_persist
from m3_tpu.index import query as iq
from m3_tpu.index.namespace_index import NamespaceIndex
from m3_tpu.index.segment import Document, ImmutableSegment, MutableSegment, execute
from m3_tpu.utils import xtime

S = xtime.SECOND
BLOCK = 4 * xtime.HOUR
T0 = 1_600_000_000 * S - (1_600_000_000 * S) % BLOCK


def mk_segment(n=20):
    seg = MutableSegment()
    for i in range(n):
        seg.insert(Document(b"series-%d" % i, (
            (b"dc", b"east" if i % 2 else b"west"),
            (b"host", b"h%d" % (i % 5)),
        )))
    return ImmutableSegment.from_mutable(seg)


class TestSegmentFiles:
    def test_roundtrip_query_parity(self, tmp_path):
        seg = mk_segment()
        idx_persist.write_segment(str(tmp_path), b"ns", T0, seg)
        back = idx_persist.read_segment(str(tmp_path), b"ns", T0)
        for q in [iq.new_term(b"dc", b"east"),
                  iq.new_regexp(b"host", b"h[12]"),
                  iq.new_conjunction(iq.new_term(b"dc", b"west"),
                                     iq.new_term(b"host", b"h0"))]:
            want = {seg.doc(int(p)).id for p in execute(seg, q)}
            got = {back.doc(int(p)).id for p in execute(back, q)}
            assert got == want, q

    def test_digest_detects_corruption(self, tmp_path):
        seg = mk_segment(5)
        d = idx_persist.write_segment(str(tmp_path), b"ns", T0, seg)
        with open(f"{d}/segment.bin", "r+b") as f:
            f.seek(10)
            f.write(b"\xff\xff")
        with pytest.raises(IOError):
            idx_persist.read_segment(str(tmp_path), b"ns", T0)

    def test_incomplete_segment_rejected(self, tmp_path):
        seg = mk_segment(5)
        d = idx_persist.write_segment(str(tmp_path), b"ns", T0, seg)
        import os

        os.unlink(f"{d}/checkpoint")
        with pytest.raises(IOError):
            idx_persist.read_segment(str(tmp_path), b"ns", T0)
        assert idx_persist.list_segments(str(tmp_path), b"ns") == []


class TestIndexFlushBootstrap:
    def test_flush_then_bootstrap_serves_queries(self, tmp_path):
        now = {"t": T0}
        nsi = NamespaceIndex(BLOCK, clock=lambda: now["t"])
        for i in range(30):
            nsi.insert(b"m-%d" % i, {b"app": b"api" if i < 20 else b"web"},
                       T0 + (i % 3) * xtime.HOUR)
        # Block not yet cold: nothing flushes.
        assert idx_persist.flush_index(str(tmp_path), b"ns", nsi,
                                       T0 + BLOCK - 1, 30 * xtime.DAY) == []
        # Cold: flushes once, then no-ops (no double persist).
        flushed = idx_persist.flush_index(str(tmp_path), b"ns", nsi,
                                          T0 + BLOCK + 1, 30 * xtime.DAY)
        assert flushed == [T0]
        assert idx_persist.flush_index(str(tmp_path), b"ns", nsi,
                                       T0 + BLOCK + 1, 30 * xtime.DAY) == []
        # Fresh index bootstraps from disk and serves the same queries.
        nsi2 = NamespaceIndex(BLOCK, clock=lambda: now["t"])
        loaded = idx_persist.bootstrap_index(str(tmp_path), b"ns", nsi2)
        assert loaded == [T0]
        got = nsi2.query(iq.new_term(b"app", b"api"))
        want = nsi.query(iq.new_term(b"app", b"api"))
        assert set(got) == set(want) and len(got) == 20
