"""Cheap structured fuzzing of the parse/ingest boundaries (the closest
Python analog of the reference's go-fuzz corpus targets): random inputs
must produce clean, typed errors — never hangs, crashes, or silent
acceptance of garbage."""

import json
import string

import numpy as np
import pytest

from m3_tpu.query import promql


class FakeSock:
    """List-backed socket stand-in: raises ConnectionError at exhaustion so
    reader loops can never hang in a test."""

    def __init__(self, data):
        self.data = data

    def recv(self, n):
        out, self.data = self.data[:n], self.data[n:]
        if not out:
            raise ConnectionError("eof")
        return out


class TestPromqlParserFuzz:
    def test_random_token_soup_never_crashes(self):
        rng = np.random.default_rng(7)
        atoms = ["metric", "rate", "sum", "by", "(", ")", "[", "]", "{", "}",
                 "5m", ":", "@", "offset", "1h", "+", "-", "*", "/", "==",
                 "bool", "on", ",", '"v"', "0.5", "or", "unless", "!~", "=",
                 "1e9", "nan", "inf", "group_left", "}"]
        ok = errs = 0
        for _ in range(3000):
            n = int(rng.integers(1, 12))
            q = " ".join(str(atoms[i]) for i in rng.integers(0, len(atoms), n))
            try:
                promql.parse(q)
                ok += 1
            except promql.ParseError:
                errs += 1
            # anything else (IndexError, RecursionError, hang) fails the test
        assert ok + errs == 3000
        assert errs > 0  # the soup does hit error paths

    def test_random_bytes_never_crash(self):
        rng = np.random.default_rng(11)
        chars = string.printable
        for _ in range(2000):
            n = int(rng.integers(1, 40))
            q = "".join(chars[i] for i in rng.integers(0, len(chars), n))
            try:
                promql.parse(q)
            except promql.ParseError:
                pass

    def test_deep_nesting_bounded(self):
        # pathological nesting must error or parse, not blow the stack
        q = "(" * 400 + "x" + ")" * 400
        try:
            promql.parse(q)
        except (promql.ParseError, RecursionError):
            # RecursionError is acceptable ONLY if raised promptly as an
            # error (python guards the stack); a segfault/hang is not.
            pass


class TestMigrationReaderFuzz:
    def test_random_streams_error_cleanly(self):
        """Random byte streams through the dual-format reader: every
        outcome must be a typed error or a decoded record — never a hang
        (the reader's _fill would block on a socket; a list-backed fake
        raising ConnectionError on exhaustion makes hangs impossible) and
        never an unbounded allocation."""
        from m3_tpu.aggregator import migration

        rng = np.random.default_rng(13)
        outcomes = {"records": 0, "recoverable": 0, "fatal": 0}
        for _ in range(800):
            n = int(rng.integers(4, 80))
            blob = bytes(rng.integers(0, 256, n, dtype=np.uint8))
            r = migration.MigrationReader(FakeSock(blob))
            try:
                r.read_entries()
                outcomes["records"] += 1
            except migration.RecoverableRecordError:
                outcomes["recoverable"] += 1
            except (ValueError, ConnectionError, KeyError, EOFError):
                outcomes["fatal"] += 1
        assert outcomes["fatal"] > 0
        assert sum(outcomes.values()) == 800

    def test_legacy_json_line_fuzz(self):
        from m3_tpu.aggregator import migration

        rng = np.random.default_rng(17)
        for _ in range(300):
            # random json-ish objects on the legacy line protocol
            obj = {k: int(v) for k, v in
                   zip(rng.choice(list("abcdef"), 3), rng.integers(0, 9, 3))}
            line = json.dumps(obj).encode() + b"\n"
            r = migration.MigrationReader(FakeSock(line))
            try:
                r.read_entries()
            except (migration.RecoverableRecordError, ValueError,
                    ConnectionError):
                pass


class TestWireFuzz:
    def test_random_buffers_raise_valueerror_only(self):
        """wire.decode on arbitrary bytes: ValueError (or its subclasses,
        e.g. UnicodeDecodeError from string fields) for every malformed
        buffer — struct.error from truncated fixed-width fields is
        normalized so protocol handlers catch ONE exception type."""
        from m3_tpu.rpc import wire

        rng = np.random.default_rng(5)
        ok = bad = 0
        for _ in range(1500):
            blob = bytes(rng.integers(0, 256, int(rng.integers(0, 80)),
                                      dtype=np.uint8))
            try:
                wire.decode(blob)
                ok += 1
            except ValueError:
                bad += 1
        assert ok + bad == 1500 and bad > 0

    def test_roundtrip_survives_fuzzed_payloads(self):
        from m3_tpu.rpc import wire

        rng = np.random.default_rng(19)
        for _ in range(200):
            payload = {
                "b": bytes(rng.integers(0, 256, 8, dtype=np.uint8)),
                "i": int(rng.integers(-2**62, 2**62)),
                "f": float(rng.standard_normal()),
                "l": [int(x) for x in rng.integers(0, 100, 3)],
            }
            assert wire.decode(wire.encode(payload)) == payload


    def test_deep_nesting_rejected(self):
        from m3_tpu.rpc import wire

        # ~3000 nested lists: must be a ValueError (depth cap), not a
        # RecursionError killing a handler thread
        blob = b"\x07\x01\x00\x00\x00" * 3000 + b"\x00"
        with pytest.raises(ValueError):
            wire.decode(blob)
        # legitimate shallow nesting still decodes
        v = [[[{"k": [1, 2]}]]]
        assert wire.decode(wire.encode(v)) == v

    def test_non_dict_frame_drops_connection_not_thread(self):
        """A well-formed frame whose top value isn't a dict must close the
        connection without a handler traceback (node_server shape check)."""
        import io
        import socket
        import struct
        import sys

        from m3_tpu.parallel.sharding import ShardSet
        from m3_tpu.rpc import wire
        from m3_tpu.rpc.node_server import NodeServer, NodeService
        from m3_tpu.storage.database import Database

        db = Database(ShardSet(2), clock=lambda: 0)
        db.mark_bootstrapped()
        srv = NodeServer(NodeService(db)).start()
        host, port = srv.address
        errbuf = io.StringIO()
        old = sys.stderr
        sys.stderr = errbuf
        try:
            for payload in (wire.encode(None), wire.encode(123),
                            wire.encode([1, 2])):
                with socket.create_connection((host, port), timeout=5) as s:
                    s.sendall(struct.pack("<I", len(payload)) + payload)
                    s.settimeout(5)
                    with pytest.raises((ConnectionError, socket.timeout,
                                        ValueError)):
                        wire.read_frame(s)
            with socket.create_connection((host, port), timeout=5) as s:
                wire.write_frame(s, {"id": 1, "m": "health", "a": {}})
                assert wire.read_frame(s)["ok"]
        finally:
            sys.stderr = old
            srv.close()
        assert "Traceback" not in errbuf.getvalue()


    def test_non_dict_frame_all_servers(self):
        """The shared read_dict_frame guard covers every server loop: a
        valid frame with a non-dict top value drops the connection on the
        KV service too (was an AttributeError traceback)."""
        import io
        import socket
        import struct
        import sys

        from m3_tpu.cluster.kv import MemStore
        from m3_tpu.cluster.kv_service import KVServer
        from m3_tpu.rpc import wire

        srv = KVServer(MemStore()).start()
        host, _, port = srv.endpoint.rpartition(":")
        port = int(port)
        errbuf = io.StringIO()
        old = sys.stderr
        sys.stderr = errbuf
        try:
            payload = wire.encode(123)
            with socket.create_connection((host, port), timeout=5) as s:
                s.sendall(struct.pack("<I", len(payload)) + payload)
                s.settimeout(5)
                with pytest.raises((ConnectionError, socket.timeout,
                                    ValueError)):
                    wire.read_frame(s)
        finally:
            sys.stderr = old
            srv.close()
        assert "Traceback" not in errbuf.getvalue()

    def test_encode_depth_cap_fails_at_sender(self):
        from m3_tpu.rpc import wire

        v = None
        for _ in range(80):
            v = [v]
        with pytest.raises(ValueError):
            wire.encode(v)

    def test_truncated_frames_raise_wire_truncated(self):
        """A peer dying at ANY byte offset inside a frame (header or
        body) surfaces as the single typed WireTruncated — never a
        struct.error or short-read garbage — so retriers classify it as
        a retryable transport failure."""
        import socket
        import struct

        from m3_tpu.rpc import wire
        from m3_tpu.rpc.wire import WireTruncated

        body = wire.encode({"k": [1, 2.5, b"x" * 20, "s"],
                            "arr": np.arange(4, dtype=np.int64)})
        frame = struct.pack("<I", len(body)) + body
        rng = np.random.default_rng(23)
        cuts = {1, 2, 3, 4, len(frame) - 1} | {
            int(c) for c in rng.integers(1, len(frame), 30)}
        for cut in sorted(cuts):
            a, b = socket.socketpair()
            a.sendall(frame[:cut])
            a.close()
            b.settimeout(5)
            with pytest.raises(WireTruncated):
                wire.read_frame(b)
            b.close()

    def test_oversized_frame_length_rejected(self):
        """A corrupt length prefix past MAX_FRAME is a typed ValueError
        BEFORE any allocation or read of the announced body."""
        import socket
        import struct

        from m3_tpu.rpc import wire

        for n in (wire.MAX_FRAME + 1, 0xFFFFFFFF):
            a, b = socket.socketpair()
            a.sendall(struct.pack("<I", n))
            b.settimeout(5)
            with pytest.raises(ValueError):
                wire.read_frame(b)
            a.close()
            b.close()

    def test_frame_mutations_only_typed_errors(self):
        """Random frame mutations (bit flips, length corruption, tail
        truncation) through a real socket: read_frame yields a decoded
        value, ValueError, or ConnectionError — nothing else, ever."""
        import socket
        import struct

        from m3_tpu.rpc import wire

        rng = np.random.default_rng(31)
        base = wire.encode({"m": "w", "a": {"ids": [b"a", b"b"],
                                            "vals": [1.0, 2.0]}})
        outcomes = {"ok": 0, "value": 0, "conn": 0}
        for _ in range(120):
            blob = bytearray(struct.pack("<I", len(base)) + base)
            mode = int(rng.integers(0, 3))
            if mode == 0:    # flip a byte anywhere
                i = int(rng.integers(0, len(blob)))
                blob[i] ^= int(rng.integers(1, 256))
            elif mode == 1:  # corrupt the length prefix
                blob[int(rng.integers(0, 4))] ^= int(rng.integers(1, 256))
            else:            # truncate the tail
                blob = blob[: int(rng.integers(1, len(blob)))]
            a, b = socket.socketpair()
            a.sendall(bytes(blob))
            a.close()
            b.settimeout(5)
            try:
                wire.read_frame(b)
                outcomes["ok"] += 1
            except ConnectionError:
                outcomes["conn"] += 1
            except ValueError:
                outcomes["value"] += 1
            finally:
                b.close()
        assert sum(outcomes.values()) == 120
        assert outcomes["conn"] > 0 and outcomes["value"] > 0


class TestTbatchDispatchFuzz:
    """Malformed columnar timed-batch frames through dispatch_entry: every
    outcome is a typed error counted by the server's per-entry handler or
    a clean (possibly partial-free) ingest — never a crash that kills the
    connection thread, and NEVER a partial ingest on a frame that errors
    (all-or-nothing contract, server.py dispatch_timed_batch)."""

    def _agg(self):
        from m3_tpu.aggregator import Aggregator, CaptureHandler

        S = 1_000_000_000
        return Aggregator(num_shards=4, clock=lambda: 1_700_000_000 * S,
                          flush_handler=CaptureHandler())

    def test_fuzzed_tbatch_frames(self):
        from m3_tpu.aggregator.server import dispatch_entry

        S = 1_000_000_000
        t0 = 1_700_000_000 * S
        rng = np.random.default_rng(29)
        mutations = [
            lambda f: f.pop("ids"),
            lambda f: f.pop("times"),
            lambda f: f.pop("values"),
            lambda f: f.update(ids=f["ids"][:-1]),          # ragged
            lambda f: f.update(times=f["times"][:-1]),      # ragged
            lambda f: f.update(mtype=99),                   # bad type
            lambda f: f.update(policy="nonsense"),          # bad policy
            lambda f: f.update(policy=123),                 # wrong type
            lambda f: f.update(ids=[*f["ids"][:-1], "str"]),  # non-bytes id
            lambda f: f.update(times="not-an-array"),
            lambda f: f.update(values=None),
            # element-level shapes the pre-round-6 validator admitted and
            # then crashed on (or silently mis-ingested) MID-LOOP:
            lambda f: f.update(times=[*map(int, f["times"][:-1]), "x"]),
            lambda f: f.update(values=[*map(float, f["values"][:-1]), None]),
            lambda f: f.update(times=[*map(int, f["times"][:-1]), [1, 2]]),
            lambda f: f.update(values=object()),            # no len/iter
        ]
        for i in range(len(mutations) * 3):
            agg = self._agg()
            n = int(rng.integers(1, 8))
            frame = {"t": "tbatch", "mtype": 1, "policy": "10s:2d",
                     "agg_id": 0,
                     "ids": [b"fz.%d" % j for j in range(n)],
                     "times": np.full(n, t0, np.int64),
                     "values": np.arange(n, dtype=np.float64)}
            mutations[i % len(mutations)](frame)
            try:
                dispatch_entry(agg, frame)
            except Exception:  # noqa: BLE001 - typed by the server handler
                # the all-or-nothing contract: an erroring frame must not
                # have staged ANY entries
                assert agg.num_entries() == 0, (
                    f"partial ingest from mutation {i % len(mutations)}")

    def test_valid_tbatch_through_dispatch(self):
        from m3_tpu.aggregator.server import dispatch_entry

        S = 1_000_000_000
        t0 = 1_700_000_000 * S
        agg = self._agg()
        dispatch_entry(agg, {
            "t": "tbatch", "mtype": 1, "policy": "10s:2d", "agg_id": 0,
            "ids": [b"ok.1", b"ok.2"], "times": np.full(2, t0, np.int64),
            "values": np.array([1.0, 2.0])})
        assert agg.num_entries() == 2

    def test_mixed_buffer_ids_ingest_fully(self):
        """bytearray/memoryview metric IDs are valid wire buffers: a
        mixed-type id column must ingest EVERY row (normalized to bytes
        during validation), not crash on the first non-bytes id after a
        prefix was aggregated (the round-5 partial-ingest hazard)."""
        from m3_tpu.aggregator.server import dispatch_entry

        S = 1_000_000_000
        t0 = 1_700_000_000 * S
        agg = self._agg()
        dispatch_entry(agg, {
            "t": "tbatch", "mtype": 1, "policy": "10s:2d", "agg_id": 0,
            "ids": [b"mix.a", bytearray(b"mix.b"), memoryview(b"mix.c")],
            "times": np.full(3, t0, np.int64),
            "values": np.array([1.0, 2.0, 3.0])})
        assert agg.num_entries() == 3
        # same id through different buffer types lands on ONE entry
        agg2 = self._agg()
        dispatch_entry(agg2, {
            "t": "tbatch", "mtype": 1, "policy": "10s:2d", "agg_id": 0,
            "ids": [b"mix.same", bytearray(b"mix.same")],
            "times": np.full(2, t0, np.int64),
            "values": np.array([1.0, 2.0])})
        assert agg2.num_entries() == 1

    def test_non_numeric_mid_array_rejected_whole(self):
        """List-typed columns with a bad element PAST the first position
        must reject with zero entries staged — the length check alone
        used to admit them and raise mid-loop."""
        import pytest as _pytest

        from m3_tpu.aggregator.server import dispatch_entry

        S = 1_000_000_000
        t0 = 1_700_000_000 * S
        for col, bad in (("times", [t0, "x", t0]),
                         ("values", [0.5, None, 1.5]),
                         ("values", [0.5, [1.0], 1.5])):
            agg = self._agg()
            frame = {"t": "tbatch", "mtype": 1, "policy": "10s:2d",
                     "agg_id": 0,
                     "ids": [b"nn.1", b"nn.2", b"nn.3"],
                     "times": [t0, t0, t0], "values": [1.0, 2.0, 3.0]}
            frame[col] = bad
            with _pytest.raises(ValueError):
                dispatch_entry(agg, frame)
            assert agg.num_entries() == 0
