"""Mesh-sharded aggregator flush: the columnar/mesh production path
(list.py collect_into + emit_batch, parallel/agg_flush quantile
ordering) must be BIT-identical to the retained host oracle
(reduce_and_emit_ref) across counter/gauge/timer mixes, empty/NaN
windows, and pipeline forwarding — plus the batched planes that ride
the rebuild: per-destination forward batching, one-publish-per-shard
columnar handling, and the one-transaction flush-times commit.

The 8-virtual-device mesh route is exercised by scripts/agg_smoke.py
and the agg benches (check_all runs them under
--xla_force_host_platform_device_count=8); these tests prove the shared
kernel's routes agree and the tier's semantics on any device count.
"""

import numpy as np
import pytest

from m3_tpu.aggregator import elem as elem_mod
from m3_tpu.aggregator import list as list_mod
from m3_tpu.aggregator.flush import FlushTimesManager, plan_jobs
from m3_tpu.cluster import kv as cluster_kv
from m3_tpu.metrics import aggregation as magg
from m3_tpu.metrics.metric import MetricType
from m3_tpu.metrics.pipeline import Op, Pipeline
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.transformation import TransformType

S = 1_000_000_000
POL = StoragePolicy.parse("1m:40h")
BASE = 1_700_000_000 * S - (1_700_000_000 * S) % (60 * S)


def _build_population(seed: int, n: int = 240):
    """Seeded mixed elem population: counters, gauges, timers (default
    suffixed agg set incl. quantiles), explicit agg sets (stdev/mean/
    sumsq/minmax), transform and rollup pipelines; windows with empty
    and NaN values."""
    rng = np.random.default_rng(seed)
    lists = list_mod.MetricLists()
    lst = lists.for_resolution(60 * S)
    elems = []
    for i in range(n):
        kind = int(rng.integers(0, 6))
        if kind == 0:
            key = elem_mod.ElemKey(b"t.c.%d" % i, POL)
            mt = MetricType.COUNTER
        elif kind == 1:
            key = elem_mod.ElemKey(b"t.g.%d" % i, POL)
            mt = MetricType.GAUGE
        elif kind == 2:
            key = elem_mod.ElemKey(b"t.t.%d" % i, POL)
            mt = MetricType.TIMER
        elif kind == 3:
            key = elem_mod.ElemKey(b"t.x.%d" % i, POL, magg.AggID.compress(
                [magg.AggType.MEAN, magg.AggType.STDEV, magg.AggType.SUMSQ,
                 magg.AggType.MIN, magg.AggType.MAX, magg.AggType.P99]))
            mt = MetricType.TIMER
        elif kind == 4:
            # PerSecond transform then rollup: exercises prev-window
            # state threading AND the forward plane
            pipe = Pipeline((
                Op.transform(TransformType.PERSECOND),
                Op.roll(b"t.roll.%d" % (i % 5), (b"host",),
                        magg.AggID.compress([magg.AggType.SUM])),
            ))
            key = elem_mod.ElemKey(
                b"t.p.%d" % i, POL,
                magg.AggID.compress([magg.AggType.LAST]), pipe)
            mt = MetricType.GAUGE
        else:
            key = elem_mod.ElemKey(b"t.e.%d" % i, POL)
            mt = MetricType.GAUGE
        e = lst.get_or_create(key, lambda k=key, m=mt: elem_mod.Elem(k, m))
        nw = int(rng.integers(1, 4))
        for w in range(nw):
            nv = int(rng.integers(0, 8)) if kind != 5 else 0  # kind 5: empty
            vals = rng.lognormal(0, 1, nv)
            if nv and rng.random() < 0.3:
                vals[int(rng.integers(0, nv))] = np.nan
            e.add_values(BASE + w * 60 * S, vals)
        elems.append(e)
    return lists, lst, elems


def _run(lists, lst, use_ref: bool):
    sink = []
    cap = lambda mid, t, v, p, _s=sink: _s.append((mid, t, v, str(p)))  # noqa: E731

    def fwd(new_id, t, v, meta, src, _s=sink):
        _s.append((b"FWD:" + new_id, t, v,
                   str(meta.storage_policy) + ":" + src.decode()))

    target = BASE + 10 * 60 * S
    if use_ref:
        jobs, _ = plan_jobs(lists, target, 0, cap, fwd)
        list_mod.reduce_and_emit_ref(jobs)
    else:
        lst.flush(target, cap, fwd)
    return sink


def _eq(a, b):
    return a == b or (a[0] == b[0] and a[1] == b[1] and a[3] == b[3]
                      and np.isnan(a[2]) and np.isnan(b[2]))


@pytest.mark.parametrize("seed", range(16))
def test_mesh_flush_bit_identical_to_ref(seed):
    got = _run(*_build_population(seed)[:2], use_ref=False)
    want = _run(*_build_population(seed)[:2], use_ref=True)
    assert len(got) == len(want)
    got_s, want_s = sorted(got, key=repr), sorted(want, key=repr)
    for g, w in zip(got_s, want_s):
        assert _eq(g, w), (seed, g, w)


def test_transform_state_threads_across_flush_rounds():
    """PerSecond's prev-window datapoint must thread identically through
    the columnar path across SUCCESSIVE flushes (the stateful pipeline
    path stays per-elem)."""
    for use_ref in (False, True):
        lists, lst, _ = _build_population(101)
        sinks = []
        for rnd in range(2):
            # stage one more window per elem, then flush
            for e in lst.elems():
                e.add_values(BASE + (5 + rnd) * 60 * S,
                             np.full(3, float(rnd + 1)))
            sinks.append(_run(lists, lst, use_ref))
        if use_ref:
            want = sinks
        else:
            got = sinks
    for g, w in zip(got, want):
        assert sorted(g, key=repr) == pytest.approx(
            sorted(w, key=repr), abs=0) or len(g) == len(w)
        for a, b in zip(sorted(g, key=repr), sorted(w, key=repr)):
            assert _eq(a, b)


def test_forwarding_is_batched_and_window_ordered():
    """emit_batch collects the round's rollup forwards into ONE
    forward_batch call (when the sink supports it), with each elem's
    windows in ascending time order (binary transforms depend on it)."""
    lists, lst, _ = _build_population(7)

    calls = []

    class BatchSink:
        def __call__(self, *a):
            raise AssertionError("per-item forward must not be used")

        def forward_batch(self, items):
            calls.append(list(items))

    n = lst.flush(BASE + 10 * 60 * S, lambda *a: None, BatchSink())
    assert n > 0
    assert len(calls) == 1  # one batch per flush round
    per_elem = {}
    for new_id, t, v, meta, src in calls[0]:
        per_elem.setdefault((src, new_id), []).append(t)
    assert per_elem, "population always includes rollup pipelines"
    for times in per_elem.values():
        assert times == sorted(times)


def test_forward_batch_groups_per_destination():
    """ForwardedWriter.forward_batch coalesces a round's forwards into
    one send_forwarded_batch per (destination, meta group) and counts
    undelivered items."""
    from m3_tpu.aggregator.aggregator import Aggregator, ForwardedWriter
    from m3_tpu.cluster.placement import (Instance, Placement,
                                          ShardAssignment, ShardState)
    from m3_tpu.metrics.metadata import ForwardMetadata

    agg = Aggregator(num_shards=4)

    class FakeTransport:
        def __init__(self, ok=True):
            self.frames = []
            self.ok = ok

        def send_forwarded(self, *a):
            raise AssertionError("batched path must be used")

        def send_forwarded_batch(self, metric_type, rows):
            self.frames.append(list(rows))
            return self.ok

    inst_a = Instance("other", "e:1", shards={
        s: ShardAssignment(s, ShardState.AVAILABLE) for s in range(4)})
    placement = Placement({"other": inst_a}, num_shards=4, replica_factor=1)
    tr = FakeTransport()
    fw = ForwardedWriter(agg)
    fw.set_routing(lambda: placement, {"other": tr}, "me")
    meta = ForwardMetadata(0, POL, Pipeline(), b"src", 1)
    items = [(b"roll.%d" % i, BASE, float(i), meta, b"src.%d" % i)
             for i in range(8)]
    fw.forward_batch(items)
    assert len(tr.frames) == 1  # one frame per destination per meta group
    assert sum(len(f) for f in tr.frames) == 8
    assert fw.dropped == 0
    # a failed frame counts every row dropped
    tr2 = FakeTransport(ok=False)
    fw.set_routing(lambda: placement, {"other": tr2}, "me")
    fw.forward_batch(items[:3])
    assert fw.dropped == 3


def test_fbatch_wire_round_trip():
    """forwarded_batch_to_wire -> codec -> dispatch_forwarded_batch
    lands every partial, all-or-nothing on malformed columns."""
    from m3_tpu.aggregator.aggregator import Aggregator
    from m3_tpu.aggregator.server import (dispatch_forwarded_batch,
                                          forwarded_batch_to_wire)
    from m3_tpu.metrics.metadata import ForwardMetadata
    from m3_tpu.rpc import wire

    meta = ForwardMetadata(0, POL, Pipeline(), b"src", 1)
    rows = [(b"r.%d" % i, BASE + i, float(i), meta, b"s.%d" % i)
            for i in range(5)]
    frame = wire.decode(wire.encode(
        forwarded_batch_to_wire(MetricType.GAUGE, rows)))
    agg = Aggregator(num_shards=4)
    dispatch_forwarded_batch(agg, frame)
    assert agg.num_entries() == 5
    bad = dict(frame)
    bad["values"] = np.asarray(bad["values"])[:2]
    agg2 = Aggregator(num_shards=4)
    with pytest.raises(ValueError):
        dispatch_forwarded_batch(agg2, bad)
    assert agg2.num_entries() == 0  # nothing partially applied


def test_producer_handler_one_publish_per_shard():
    """handle_columnar ships ONE publish per topic shard per flush
    round; decode_aggregated_batch restores every datapoint."""
    from m3_tpu.aggregator.handler import (ProducerHandler,
                                           decode_aggregated_batch)

    published = []

    class FakeProducer:
        def publish(self, shard, payload):
            published.append((shard, payload))
            return len(published)

    h = ProducerHandler(FakeProducer(), num_shards=4)
    ids = [b"m.%d" % i for i in range(64)]
    times = np.arange(64, dtype=np.int64) + BASE
    values = np.arange(64, dtype=np.float64) / 7.0
    h.handle_columnar([(ids, times, values, POL)])
    shards = {s for s, _ in published}
    assert len(published) == len(shards) <= 4  # one publish per shard
    assert h.publishes == len(published)
    decoded = [m for _, p in published for m in decode_aggregated_batch(p)]
    assert sorted(m.id for m in decoded) == sorted(ids)
    by_id = {m.id: m for m in decoded}
    for i, mid in enumerate(ids):
        m = by_id[mid]
        assert m.time_nanos == int(times[i])
        assert m.value == float(values[i])
        assert m.storage_policy == POL


def test_flush_times_store_many_single_transaction():
    """The round's flush times land as ONE kv set_many (one version bump
    per key, readable via the unbatched get path)."""
    store = cluster_kv.MemStore()
    calls = {"set": 0, "set_many": 0}
    orig_set, orig_many = store.set, store.set_many

    def spy_set(key, data):
        calls["set"] += 1
        return orig_set(key, data)

    def spy_many(items):
        calls["set_many"] += 1
        return orig_many(items)

    store.set, store.set_many = spy_set, spy_many
    mgr = FlushTimesManager(store, "ss-0")
    mgr.store_many({sid: {60 * S: BASE + sid} for sid in range(8)})
    assert calls == {"set": 0, "set_many": 1}
    for sid in range(8):
        assert mgr.get(sid) == {60 * S: BASE + sid}

    class NoBatchStore:
        """A store speaking only the unbatched kv surface (e.g. the
        remote kv client): store_many must fall back to per-shard sets."""

        def __init__(self):
            self.sets = []

        def set(self, key, data):
            self.sets.append(key)
            return 1

    nb = NoBatchStore()
    FlushTimesManager(nb, "ss-1").store_many({0: {60 * S: 1}, 1: {60 * S: 2}})
    assert len(nb.sets) == 2


def test_aggregator_flush_commits_flush_times_once():
    """A managed multi-shard Aggregator.flush batches every shard's
    flush-times into one store_many call."""
    from m3_tpu.aggregator.aggregator import Aggregator
    from m3_tpu.aggregator.election import ElectionManager
    from m3_tpu.cluster.services import LeaderService

    store = cluster_kv.MemStore()
    many = []
    orig = store.set_many
    store.set_many = lambda items: (many.append(len(items)), orig(items))[1]
    ftimes = FlushTimesManager(store, "ss")
    from m3_tpu.aggregator.handler import CaptureHandler

    cap = CaptureHandler()
    clock = {"t": BASE}
    leader = LeaderService(store, "agg-election", "i-0",
                           lease_ttl_ns=3600 * S, clock=lambda: clock["t"])
    election = ElectionManager(leader)
    agg = Aggregator(num_shards=8, clock=lambda: clock["t"],
                     flush_handler=cap, election=election,
                     flush_times=ftimes,
                     default_policies=(POL,))
    for i in range(64):
        agg.add_timed(MetricType.GAUGE, b"ten.m.%d" % i, BASE, float(i), POL)
    clock["t"] = BASE + 2 * 60 * S
    n = agg.flush()
    assert n == 64
    assert len(cap.metrics) == 64
    assert len(many) == 1  # ONE kv transaction for the whole round
    used_shards = {agg.shard_for(b"ten.m.%d" % i) for i in range(64)}
    stored = {sid for sid in range(8) if ftimes.get(sid)}
    assert stored == set(range(8)) or stored >= used_shards


def test_quantile_routes_agree_and_exact_values():
    """parallel/agg_flush.exact_quantile_values == the oracle's
    _quantile_rows_for on ragged NaN-bearing buckets (shared kernel,
    f64 host gather)."""
    from m3_tpu.parallel import agg_flush

    rng = np.random.default_rng(3)
    buckets = []
    for i in range(40):
        nv = int(rng.integers(0, 12))
        b = rng.lognormal(0, 1, nv)
        if nv and rng.random() < 0.4:
            b[int(rng.integers(0, nv))] = np.nan
        buckets.append(b)
    qs = (0.5, 0.95, 0.99)
    counts = np.array([b.size for b in buckets], dtype=np.int64)
    got = agg_flush.exact_quantile_values(buckets, counts, qs)
    want_rows = list_mod._quantile_rows_for(buckets, qs)
    for i, row in enumerate(want_rows):
        for j, q in enumerate(qs):
            w = row[q]
            g = got[i, j]
            assert g == w or (np.isnan(g) and np.isnan(w)), (i, q)


def test_quantile_rows_keyed_by_tuple_index():
    """MEDIAN and P50 share q=0.5: both must read the SAME position of
    the elem's _quantiles tuple (index keying — a recomputed float can
    never miss)."""
    key = elem_mod.ElemKey(b"t.q", POL, magg.AggID.compress(
        [magg.AggType.MEDIAN, magg.AggType.P50, magg.AggType.P99]))
    e = elem_mod.Elem(key, MetricType.TIMER)
    assert e._quantiles == (0.5, 0.99)
    assert e._q_idx[magg.AggType.MEDIAN] == 0
    assert e._q_idx[magg.AggType.P50] == 0
    assert e._q_idx[magg.AggType.P99] == 1
    out = []
    e.emit(BASE, {k: 0.0 for k in list_mod._STAT_KEYS} | {"count": 3.0},
           (41.5, 99.25), lambda mid, t, v, p: out.append((mid, v)))
    got = {mid: v for mid, v in out}
    assert got[b"t.q.median"] == 41.5
    assert got[b"t.q.p50"] == 41.5
    assert got[b"t.q.p99"] == 99.25


def test_emit_class_interned_and_elem_staging():
    """Elems with one emission signature share ONE interned EmitClass;
    staging degrades (and recovers on an empty full drain) through
    chunked and out-of-order adds."""
    k1 = elem_mod.ElemKey(b"a.x", POL)
    k2 = elem_mod.ElemKey(b"b.y", POL)
    e1 = elem_mod.Elem(k1, MetricType.COUNTER)
    e2 = elem_mod.Elem(k2, MetricType.COUNTER)
    assert e1._eclass is e2._eclass
    # multi-add chunking + out-of-order windows still flush exactly
    e1.add_values(BASE + 60 * S, np.array([1.0, 2.0]))
    e1.add_values(BASE, np.array([3.0]))          # out of order
    e1.add_values(BASE + 60 * S, np.array([4.0]))  # chunked
    assert e1._degraded
    batch = list_mod.FlushBatch()
    lst = list_mod.MetricList(60 * S)
    lst._elems[k1] = e1
    n, _ = lst.collect_into(BASE + 10 * 60 * S, batch)
    assert n == 2
    out = []
    list_mod.emit_batch(batch, lambda mid, t, v, p: out.append((t, v)))
    assert sorted(out) == [(BASE + 60 * S, 3.0), (BASE + 2 * 60 * S, 7.0)]
    assert not e1._degraded and e1.is_empty()  # reset on empty drain
    # a degraded elem keeps flushing exactly on later rounds
    e1.add_values(BASE + 2 * 60 * S, np.array([5.0]))
    batch2 = list_mod.FlushBatch()
    n2, _ = lst.collect_into(BASE + 20 * 60 * S, batch2)
    assert n2 == 1
    out2 = []
    list_mod.emit_batch(batch2, lambda mid, t, v, p: out2.append((t, v)))
    assert out2 == [(BASE + 3 * 60 * S, 5.0)]
