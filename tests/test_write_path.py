"""Write-path insert queue + mesh-routed flush encode.

Covers the shard/index insert-queue rebuild (reference:
src/dbnode/storage/shard_insert_queue.go, storage/index/
index_insert_queue.go): sync read-your-write, async visible-after-one-
drain, shutdown drains, bounded-depth shedding via Backpressure, writes
racing tick/seal losing nothing, a 16-thread mixed new/known-series
hammer against the synchronous oracle, and the serving flush's
shard x time mesh encode being bit-identical to the single-device path
(parallel.ingest.flush_encode_prepared on the 8-device virtual mesh)."""

import threading

import numpy as np
import pytest

from m3_tpu.index import query as iq
from m3_tpu.index.namespace_index import NamespaceIndex
from m3_tpu.parallel import ingest as par_ingest
from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.storage import block as storage_block
from m3_tpu.storage.block import encode_block, merge_same_start
from m3_tpu.storage.database import Database
from m3_tpu.storage.insert_queue import InsertGroup, InsertQueue
from m3_tpu.storage.namespace import NamespaceOptions
from m3_tpu.storage.shard import Shard, ShardOptions
from m3_tpu.utils import xtime
from m3_tpu.utils.health import Priority
from m3_tpu.utils.limits import Backpressure

S = 1_000_000_000
T0 = 1_700_000_000 * S
BLOCK = 2 * xtime.HOUR


def make_db(num_shards=8, clock=None, **ns_opts):
    clock = clock or (lambda: T0)
    db = Database(ShardSet(num_shards), clock=clock)
    db.create_namespace(b"default", NamespaceOptions(**ns_opts),
                        index=NamespaceIndex(clock=clock))
    return db


def total_points(db, ids, start=T0 - xtime.DAY, end=T0 + xtime.DAY):
    return sum(len(db.read(b"default", sid, start, end)[0]) for sid in ids)


class TestQueueLifecycle:
    def test_sync_read_your_write(self):
        """Default mode: write_batch returns only after the queue drain —
        buffer, registry AND reverse index are all visible."""
        db = make_db()
        ids = [b"ryw-%d" % i for i in range(20)]
        tags = [{b"app": b"ryw", b"n": b"%d" % i} for i in range(20)]
        db.write_batch(b"default", ids, np.full(20, T0, np.int64),
                       np.arange(20.0), tags=tags)
        for i in (0, 7, 19):
            t, v = db.read(b"default", ids[i], T0 - 1, T0 + 1)
            np.testing.assert_array_equal(v, [float(i)])
        assert sorted(db.query_ids(b"default", iq.new_term(b"app", b"ryw"))) \
            == sorted(ids)

    def test_async_visible_after_one_drain(self):
        db = make_db(write_new_series_async=True)
        ids = [b"async-%d" % i for i in range(10)]
        db.write_batch(b"default", ids, np.full(10, T0, np.int64),
                       np.ones(10), tags=[{b"app": b"async"}] * 10)
        # Not yet drained: reads miss, the queue holds the entries.
        assert total_points(db, ids) == 0
        ns = db.namespace(b"default")
        assert sum(s.insert_queue.pending() for s in ns.shards.values()) == 10
        assert db.query_ids(b"default", iq.new_term(b"app", b"async")) == []
        db.tick()  # tick drains before sealing
        assert total_points(db, ids) == 10
        assert sorted(db.query_ids(b"default", iq.new_term(b"app", b"async"))) \
            == sorted(ids)

    def test_shutdown_drains_queue(self):
        db = make_db(write_new_series_async=True)
        ids = [b"shut-%d" % i for i in range(8)]
        db.write_batch(b"default", ids, np.full(8, T0, np.int64),
                       np.ones(8), tags=[{b"app": b"shut"}] * 8)
        assert total_points(db, ids) == 0
        db.close()  # stop() drains even without a background thread
        assert total_points(db, ids) == 8
        assert sorted(db.query_ids(b"default", iq.new_term(b"app", b"shut"))) \
            == sorted(ids)

    def test_background_drainer(self):
        """start() opts into the reference's dedicated-drainer shape:
        async inserts become visible without any tick."""
        db = make_db(write_new_series_async=True)
        ns = db.namespace(b"default")
        sid = b"bg-series"
        shard = ns.shard_for(db.shard_set.lookup(sid))
        shard.insert_queue.start()
        try:
            db.write(b"default", sid, T0, 5.0, tags={b"app": b"bg"})
            deadline = threading.Event()
            for _ in range(200):
                if len(db.read(b"default", sid, T0 - 1, T0 + 1)[0]):
                    break
                deadline.wait(0.01)
            t, v = db.read(b"default", sid, T0 - 1, T0 + 1)
            np.testing.assert_array_equal(v, [5.0])
        finally:
            shard.insert_queue.stop()

    def test_rate_limited_drains_coalesce(self):
        """interval_ns bounds the drain rate: many inserts inside one
        interval coalesce into few batches, and nothing is lost."""
        applied = []
        q = InsertQueue(lambda groups: applied.extend(groups),
                        interval_ns=int(0.05 * 1e9))
        q.start()
        try:
            for i in range(20):
                q.insert(InsertGroup([b"rl-%d" % i], None), sync=False)
            q.stop()
        finally:
            q.stop()
        assert sum(len(g) for g in applied) == 20
        assert q.drains < 20  # coalesced, not one drain per insert

    def test_drain_error_propagates_to_sync_waiter(self):
        def boom(groups):
            raise RuntimeError("drain failed")

        q = InsertQueue(boom)
        with pytest.raises(RuntimeError, match="drain failed"):
            q.insert(InsertGroup([b"x"], None), sync=True)
        # The gate budget was still released — the queue is reusable.
        assert q.gate.depth() == 0

    def test_single_write_sync_and_known_fast_path(self):
        db = make_db()
        assert db.write(b"default", b"one", T0, 1.0, tags={b"a": b"b"}) is None
        t, v = db.read(b"default", b"one", T0 - 1, T0 + 1)
        np.testing.assert_array_equal(v, [1.0])
        # Second write takes the known-series fast path (no queue).
        ns = db.namespace(b"default")
        shard = ns.shard_for(db.shard_set.lookup(b"one"))
        drains_before = shard.insert_queue.drains
        db.write(b"default", b"one", T0 + S, 2.0)
        assert shard.insert_queue.drains == drains_before
        t, v = db.read(b"default", b"one", T0 - 1, T0 + 2 * S)
        np.testing.assert_array_equal(v, [1.0, 2.0])


class TestBackpressure:
    def opts(self, **kw):
        return ShardOptions(write_new_series_async=True,
                            insert_max_pending=10,
                            insert_high_watermark=0.75, **kw)

    def write_new(self, shard, tag, n, priority):
        ids = [b"%s-%d" % (tag, i) for i in range(n)]
        shard.write_batch(ids, np.full(n, T0, np.int64), np.ones(n), T0,
                          priority=priority)

    def test_bounded_depth_sheds_by_priority(self):
        """Seeded overload: BULK sheds at the high watermark, NORMAL at
        capacity, CRITICAL never — and a shed leaves depth untouched."""
        shard = Shard(0, self.opts())
        self.write_new(shard, b"a", 5, Priority.BULK)       # depth 5
        with pytest.raises(Backpressure):
            self.write_new(shard, b"b", 3, Priority.BULK)   # 8 > high 7.5
        assert shard.insert_queue.pending() == 5
        self.write_new(shard, b"c", 4, Priority.NORMAL)     # 9 <= 10
        with pytest.raises(Backpressure):
            self.write_new(shard, b"d", 2, Priority.NORMAL)  # 11 > 10
        self.write_new(shard, b"e", 2, Priority.CRITICAL)   # always admitted
        assert shard.insert_queue.pending() == 11
        assert shard.insert_queue.gate.shed == {"critical": 0, "normal": 2,
                                                "bulk": 3}
        shard.insert_queue.drain()
        assert shard.num_series() == 11
        assert shard.insert_queue.gate.depth() == 0

    def test_shed_batch_is_all_or_nothing(self):
        """A shed write_batch must not partially apply: the known-series
        rows of the rejected batch are NOT written either."""
        shard = Shard(0, self.opts())
        shard.write_batch([b"known"], np.array([T0]), np.array([1.0]), T0)
        shard.insert_queue.drain()
        before = len(shard.read(b"known", T0 - S, T0 + xtime.DAY)[0])
        self.write_new(shard, b"fill", 9, Priority.NORMAL)  # depth 9
        ids = [b"known", b"fresh-0", b"fresh-1"]
        with pytest.raises(Backpressure):
            shard.write_batch(ids, np.full(3, T0 + S, np.int64),
                              np.ones(3), T0, priority=Priority.NORMAL)
        assert len(shard.read(b"known", T0 - S, T0 + xtime.DAY)[0]) == before


class TestRacingTickSeal:
    def test_writes_racing_tick_lose_nothing(self):
        """Writers race a ticking clock across a seal boundary; every
        accepted sync write is readable afterwards."""
        now = {"t": T0}
        db = make_db(num_shards=4, clock=lambda: now["t"])
        written = []
        errs = []
        stop = threading.Event()

        def writer(k):
            i = 0
            try:
                while not stop.is_set():
                    sid = b"race-%d-%d" % (k, i)
                    t = now["t"]
                    try:
                        db.write_batch(b"default", [sid],
                                       np.array([t], np.int64),
                                       np.array([1.0]),
                                       tags=[{b"app": b"race"}])
                    except ValueError:
                        # The clock marched past the acceptance window
                        # between sampling and validating — a legitimate
                        # whole-batch rejection, nothing applied.
                        continue
                    written.append(sid)
                    i += 1
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        # March the clock over two seal boundaries while ticking.
        for step in range(20):
            now["t"] = T0 + step * (BLOCK // 4)
            db.tick()
        stop.set()
        for t in threads:
            t.join()
        db.close()
        db.tick(now["t"])
        assert not errs
        assert written
        # Every accepted write is readable (buffer or sealed block).
        missing = [sid for sid in written
                   if not len(db.read(b"default", sid,
                                      T0 - xtime.DAY, now["t"] + xtime.DAY)[0])]
        assert missing == []

    def test_same_start_reseal_merges(self):
        """A drain landing after its bucket sealed must MERGE into the
        existing block on the next tick, not overwrite it."""
        shard = Shard(0, ShardOptions())
        bs = (T0 // BLOCK) * BLOCK
        t1, t2 = bs + xtime.MINUTE, bs + 2 * xtime.MINUTE
        shard.write_batch([b"early"], np.array([t1], np.int64),
                          np.array([1.0]), t1)
        seal_at = bs + BLOCK + 11 * xtime.MINUTE
        shard.tick(seal_at)
        assert bs in shard.blocks and shard.blocks[bs].num_series == 1
        # Simulate the late drain: the write was accepted before the
        # boundary but its bucket re-materializes after the seal.
        idx, _ = shard.registry.get_or_create(b"late")
        shard.buffer.write_batch(np.array([idx], np.int32),
                                 np.array([t2], np.int64), np.array([2.0]))
        shard.tick(seal_at + xtime.MINUTE)
        blk = shard.blocks[bs]
        assert blk.num_series == 2  # merged, not overwritten
        t, v = shard.read(b"early", bs, bs + BLOCK)
        np.testing.assert_array_equal(v, [1.0])
        t, v = shard.read(b"late", bs, bs + BLOCK)
        np.testing.assert_array_equal(v, [2.0])

    def test_merge_same_start_last_wins(self, rng):
        """Direct merge contract: union of series; duplicate timestamps
        resolve to the later block's value."""
        w = 16
        ts = T0 + np.arange(w, dtype=np.int64)[None, :] * xtime.SECOND
        v1 = rng.standard_normal((1, w))
        v2 = rng.standard_normal((1, w))
        b1 = encode_block(T0, np.array([0], np.int32), ts, v1,
                          np.array([w], np.int32))
        b2 = encode_block(T0, np.array([0, 1], np.int32),
                          np.concatenate([ts, ts]),
                          np.concatenate([v2, v1 + 7.0]),
                          np.array([w, w], np.int32))
        merged = merge_same_start(b1, b2)
        np.testing.assert_array_equal(merged.series_indices, [0, 1])
        got_t, got_v = merged.read(0)
        np.testing.assert_array_equal(got_t, ts[0])
        np.testing.assert_allclose(got_v, v2[0])  # b2 wins duplicates
        got_t, got_v = merged.read(1)
        np.testing.assert_allclose(got_v, v1[0] + 7.0)


class TestHammerVsOracle:
    @pytest.mark.parametrize("async_mode", [False, True])
    def test_16_thread_hammer_matches_synchronous_oracle(self, async_mode):
        """16 threads hammer mixed new/known-series write_batches through
        the queue-enabled path; the final registry + index + buffer state
        must equal a single-threaded synchronous replay of the same
        logical writes. (id, t) pairs map to one deterministic value, so
        arrival order cannot change the converged state."""
        n_threads, ops = 16, 30
        pool = [b"hammer-%03d" % i for i in range(120)]
        tags = {sid: {b"app": b"hammer", b"mod": b"%d" % (i % 5)}
                for i, sid in enumerate(pool)}
        db = make_db(num_shards=4, write_new_series_async=async_mode)

        def value_of(sid, t):
            return float((hash((sid, t)) % 1000))

        all_writes = []
        lock = threading.Lock()
        errs = []

        def worker(k):
            rng = np.random.default_rng(1000 + k)
            try:
                for op in range(ops):
                    sel = rng.integers(0, len(pool), 20)
                    ids = [pool[j] for j in sel]
                    ts = np.asarray(
                        T0 - (rng.integers(0, 500, 20)) * S, np.int64)
                    vals = np.asarray([value_of(s, int(t))
                                       for s, t in zip(ids, ts)])
                    db.write_batch(b"default", ids, ts, vals,
                                   tags=[tags[s] for s in ids])
                    with lock:
                        all_writes.append((ids, ts, vals))
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        db.close()  # async mode: converge via the shutdown drain

        oracle = make_db(num_shards=4)
        for ids, ts, vals in all_writes:
            oracle.write_batch(b"default", ids, ts, vals,
                               tags=[tags[s] for s in ids])

        ns, ons = db.namespace(b"default"), oracle.namespace(b"default")
        # Registry state: same ids per shard.
        for sid_ in ns.shards:
            assert sorted(ns.shards[sid_].registry.all_ids()) == \
                sorted(ons.shards[sid_].registry.all_ids())
        # Index state: every tag query returns the oracle's id set.
        for mod in range(5):
            q = iq.new_conjunction(iq.new_term(b"app", b"hammer"),
                                   iq.new_term(b"mod", b"%d" % mod))
            assert db.query_ids(b"default", q) == \
                oracle.query_ids(b"default", q)
        # Buffer state: identical merged reads per series.
        touched = {s for ids, _, _ in all_writes for s in ids}
        for sid in sorted(touched):
            t_a, v_a = db.read(b"default", sid, T0 - xtime.DAY,
                               T0 + xtime.DAY)
            t_b, v_b = oracle.read(b"default", sid, T0 - xtime.DAY,
                                   T0 + xtime.DAY)
            np.testing.assert_array_equal(t_a, t_b)
            np.testing.assert_array_equal(v_a, v_b)


class TestMeshFlushEncode:
    def _dense(self, rng, s=32, w=64):
        ts = T0 + np.arange(w, dtype=np.int64)[None, :] * 10 * S \
            + np.zeros((s, 1), np.int64)
        vals = np.floor(rng.standard_normal((s, w)) * 100)
        return (np.arange(s, dtype=np.int32), ts, vals,
                np.full(s, w, np.int32))

    def test_mesh_encode_bit_identical_to_single_device(self, rng,
                                                        monkeypatch):
        """The serving flush's mesh-routed encode produces bit-identical
        words/nbits vs the single-device path, and the instrument counter
        proves the mesh path actually ran."""
        series, ts, vals, npts = self._dense(rng)
        counter = storage_block._FLUSH_METRICS.counter("mesh_encode")
        before = counter.value()
        assert par_ingest.flush_mesh() is not None  # 8-device virtual mesh
        mesh_blk = encode_block(T0, series, ts, vals, npts)
        assert counter.value() == before + 1
        # Single-device reference path.
        monkeypatch.setenv("M3_TPU_MESH_FLUSH", "0")
        par_ingest.flush_mesh.cache_clear()
        try:
            single_blk = encode_block(T0, series, ts, vals, npts)
            assert counter.value() == before + 1  # did NOT route
        finally:
            monkeypatch.undo()
            par_ingest.flush_mesh.cache_clear()
        np.testing.assert_array_equal(mesh_blk.words, single_blk.words)
        np.testing.assert_array_equal(mesh_blk.nbits, single_blk.nbits)
        np.testing.assert_array_equal(mesh_blk.npoints, single_blk.npoints)
        # And both decode to the original points.
        dt, dv, dn = mesh_blk.read_all()
        np.testing.assert_array_equal(dt, ts)
        np.testing.assert_array_equal(dv, vals)

    def test_tick_seal_routes_through_mesh(self, rng):
        """Shard._tick_locked's seal encode takes the mesh path when the
        padded tile divides the device count and clears the dispatch
        floor (32 series x 64 points = 2048 cells)."""
        shard = Shard(0, ShardOptions())
        bs = (T0 // BLOCK) * BLOCK
        ids = [b"mesh-%02d" % i for i in range(32)]
        base = bs + xtime.MINUTE
        for p in range(64):
            t = base + p * xtime.SECOND
            shard.write_batch(ids, np.full(32, t, np.int64),
                              np.arange(32.0) + p, t)
        counter = storage_block._FLUSH_METRICS.counter("mesh_encode")
        before = counter.value()
        shard.tick(bs + BLOCK + 11 * xtime.MINUTE)
        assert counter.value() == before + 1
        t_r, v_r = shard.read(ids[5], bs, bs + BLOCK)
        np.testing.assert_array_equal(v_r, np.arange(64.0) + 5.0)
