"""Seeded property suite for the array-native inverted index.

The contract under test: the bitmap-kernel searcher (`execute`, dual-form
postings + density-adaptive word kernels + regexp prefix-range pruning)
is RESULT-IDENTICAL to the original pure set-algebra evaluator
(`execute_ref`, kept verbatim as the oracle) across randomized segments
and query trees — including negation-only conjunctions, duplicate doc
ids across merged segments, and regexps over empty/missing fields — and
the postings-list cache returns bit-identical arrays on hits, with
seal/merge/expiry invalidating per segment generation.

test_fuzz style: every case derives from a seed, failures print it."""

import re

import numpy as np
import pytest

from m3_tpu.index import query as iq
from m3_tpu.index.namespace_index import NamespaceIndex
from m3_tpu.index.postings_cache import PostingsListCache
from m3_tpu.index.query import literal_prefix
from m3_tpu.index.segment import (
    Document,
    ImmutableSegment,
    MutableSegment,
    TermDict,
    execute,
    execute_ref,
)
from m3_tpu.utils import instrument, xtime

T0 = 1_600_000_000 * xtime.SECOND

# Alphabets chosen to stress the term dictionary's byte ordering: shared
# prefixes, embedded/trailing NULs, 0xFF bytes (prefix-successor carries),
# and empty values.
FIELDS = [b"f0", b"f1", b"f2", b"nul\x00fld"]
VALUE_PARTS = [b"", b"a", b"ab", b"abc", b"abd", b"b", b"ba", b"\x00",
               b"a\x00", b"a\x00b", b"\xff", b"\xff\xff", b"z\xff", b"zz"]
PATTERNS = [b"a.*", b"ab.*", b"a", b"", b".*", b"ab?c?", b"a\x00?b?",
            b"[ab].*", b"a.*|b.*", b"z?\xff.*", b"x.*", b"abc|abd",
            b"a+\x00*b*", b"(ab|ba).*"]


def _rand_value(rng):
    k = int(rng.integers(1, 3))
    return b"".join(VALUE_PARTS[int(rng.integers(len(VALUE_PARTS)))]
                    for _ in range(k))


def _rand_doc(rng, i):
    fields = []
    for f in FIELDS:
        if rng.random() < 0.75:  # some docs miss some fields
            fields.append((f, _rand_value(rng)))
    if rng.random() < 0.1 and fields:  # duplicate (name, value) pair
        fields.append(fields[0])
    return Document(b"doc-%05d" % i, tuple(fields))


def _rand_query(rng, depth=0):
    r = rng.random()
    field = FIELDS[int(rng.integers(len(FIELDS)))] if rng.random() < 0.9 \
        else b"missing_field"
    if depth >= 3 or r < 0.30:
        if rng.random() < 0.5:
            return iq.new_term(field, _rand_value(rng))
        return iq.new_regexp(field,
                             PATTERNS[int(rng.integers(len(PATTERNS)))])
    if r < 0.45:
        return iq.AllQuery()
    if r < 0.65:
        subs = [_rand_query(rng, depth + 1)
                for _ in range(int(rng.integers(2, 4)))]
        if rng.random() < 0.25:  # negation-only conjunction
            subs = [iq.new_negation(s) for s in subs]
        elif rng.random() < 0.5:
            subs[-1] = iq.new_negation(subs[-1])
        return iq.ConjunctionQuery(tuple(subs))
    if r < 0.85:
        return iq.DisjunctionQuery(tuple(
            _rand_query(rng, depth + 1)
            for _ in range(int(rng.integers(2, 4)))))
    return iq.new_negation(_rand_query(rng, depth + 1))


def _build_segment(rng):
    """Random segment in one of the shapes a query can meet: live
    mutable, sealed immutable, or a merge with OVERLAPPING doc ids (the
    duplicate-id compaction shape)."""
    n = int(rng.integers(1, 40))
    docs = [_rand_doc(rng, i) for i in range(n)]
    shape = int(rng.integers(3))
    if shape == 0:
        seg = MutableSegment()
        seg.insert_batch(docs)
        for d in docs[:: max(n // 4, 1)]:
            seg.insert(d)  # dedup re-inserts
        return seg
    if shape == 1:
        seg = MutableSegment()
        seg.insert_batch(docs)
        return ImmutableSegment.from_mutable(seg)
    cut_lo, cut_hi = sorted(rng.integers(0, n + 1, size=2))
    a, b = MutableSegment(), MutableSegment()
    a.insert_batch(docs[:cut_hi])
    b.insert_batch(docs[cut_lo:])  # overlap -> duplicate ids in the merge
    if not len(a):
        a.insert_batch(docs[:1])
    if not len(b):
        b.insert_batch(docs[-1:])
    return ImmutableSegment.merge([ImmutableSegment.from_mutable(a),
                                   ImmutableSegment.from_mutable(b)])


class TestBitmapVsSetAlgebra:
    def test_thousand_seeded_cases(self):
        """>= 1000 (segment, query) cases: execute == execute_ref."""
        cases = 0
        for seed in range(250):
            rng = np.random.default_rng(1000 + seed)
            seg = _build_segment(rng)
            cache = PostingsListCache(scope=instrument.Scope())
            for qi in range(5):
                q = _rand_query(rng)
                want = execute_ref(seg, q)
                got = execute(seg, q)
                got_cached = execute(seg, q, cache=cache)
                ctx = f"seed={1000 + seed} query#{qi} {q}"
                assert np.array_equal(got, want), ctx
                assert got.dtype == want.dtype == np.int32, ctx
                assert np.array_equal(got_cached, want), ctx
                cases += 1
        assert cases >= 1000

    def test_empty_field_regexps(self):
        seg = MutableSegment()
        seg.insert(Document(b"only", ((b"present", b"v"),)))
        imm = ImmutableSegment.from_mutable(seg)
        for s in (seg, imm):
            for pat in (b".*", b"", b"a.*"):
                q = iq.new_regexp(b"absent", pat)
                assert np.array_equal(execute(s, q), execute_ref(s, q))
                assert len(execute(s, q)) == 0

    def test_negation_only_conjunction_matches_ref(self):
        rng = np.random.default_rng(7)
        seg = _build_segment(rng)
        q = iq.ConjunctionQuery((
            iq.new_negation(iq.new_term(b"f0", b"a")),
            iq.new_negation(iq.new_regexp(b"f1", b"a.*")),
        ))
        assert np.array_equal(execute(seg, q), execute_ref(seg, q))

    def test_duplicate_ids_across_merge_query_path(self):
        """The namespace materialization dedups ids that a merged segment
        holds at two positions."""
        a, b = MutableSegment(), MutableSegment()
        for s in (a, b):
            s.insert(Document(b"shared", ((b"t", b"x"),)))
        b.insert(Document(b"extra", ((b"t", b"x"),)))
        merged = ImmutableSegment.merge([ImmutableSegment.from_mutable(a),
                                         ImmutableSegment.from_mutable(b)])
        pos = execute(merged, iq.new_term(b"t", b"x"))
        assert len(pos) == 3  # three postings...
        ids = merged.sorted_ids_for(pos).tolist()
        assert ids == [b"extra", b"shared"]  # ...two distinct sorted ids


class TestTermDict:
    def test_rank_matches_python_bisect(self):
        import bisect

        rng = np.random.default_rng(42)
        for _ in range(60):
            terms = sorted({_rand_value(rng)
                            for _ in range(int(rng.integers(0, 50)))})
            td = TermDict(terms)
            queries = [_rand_value(rng) for _ in range(20)] + terms[:5]
            got = td.rank(queries)
            for q, g in zip(queries, got):
                assert int(g) == bisect.bisect_left(terms, q), (terms, q)
                i = td.find(q)
                if q in terms:
                    assert terms[i] == q
                else:
                    assert i == -1

    def test_width_cap_long_terms(self):
        """Terms beyond WIDTH_CAP tie in the matrix and resolve via the
        exact-compare fallback; the padded matrix never exceeds the cap."""
        import bisect

        cap = TermDict.WIDTH_CAP
        base = b"P" * cap
        terms = sorted({base, base + b"a", base + b"ab", base + b"\x00",
                        base + b"z" * 100, base[:-1], b"Q" * 200,
                        b"Q" * 200 + b"x", b"short", b""})
        td = TermDict(terms)
        assert td.width == cap and td.padded.shape[1] == cap
        queries = terms + [base + b"b", base + b"\x00\x00", b"Q" * 199,
                           b"Q" * 201, b"P", b"R", base + b"z" * 99]
        for q in queries:
            assert int(td.rank([q])[0]) == bisect.bisect_left(terms, q), q
            assert (td.find(q) >= 0) == (q in terms), q
            if q in terms:
                assert terms[td.find(q)] == q
        for prefix in (base, base + b"a", b"Q" * 100, b"P", b""):
            lo, hi = td.prefix_range(prefix)
            assert terms[lo:hi] == [t for t in terms
                                    if t.startswith(prefix)], prefix

    def test_prefix_range_matches_scan(self):
        rng = np.random.default_rng(43)
        for _ in range(40):
            terms = sorted({_rand_value(rng)
                            for _ in range(int(rng.integers(1, 60)))})
            td = TermDict(terms)
            for prefix in (b"", b"a", b"ab", b"\xff", b"z\xff", b"a\x00",
                           _rand_value(rng)):
                lo, hi = td.prefix_range(prefix)
                want = [t for t in terms if t.startswith(prefix)]
                assert terms[lo:hi] == want, (terms, prefix)


class TestLiteralPrefix:
    @pytest.mark.parametrize("pattern,prefix", [
        (b"abc.*", b"abc"),
        (b"abc", b"abc"),
        (b"ab?c", b"a"),
        (b"ab*", b"a"),
        (b"ab{2,3}", b"a"),
        (b"ab+", b"ab"),
        (b"a|b", b""),
        (b"abc|abd", b""),
        (b"a(b|c)", b""),  # conservative: any "|" voids the prefix
        (b"a(bc)d", b"a"),
        (b".*", b""),
        (b"", b""),
        (b"a\\d+", b"a"),
        (b"^a", b""),
        (b"a[bc]d", b"a"),
    ])
    def test_prefix_extraction(self, pattern, prefix):
        assert literal_prefix(pattern) == prefix

    def test_prefix_is_sound_on_random_patterns(self):
        """Every fullmatch-accepted string starts with the extracted
        prefix — the prune can only narrow, never lose matches."""
        rng = np.random.default_rng(44)
        values = [_rand_value(rng) for _ in range(300)] + list(VALUE_PARTS)
        for pat in PATTERNS:
            p = literal_prefix(pat)
            cre = re.compile(pat)
            for v in values:
                if cre.fullmatch(v):
                    assert v.startswith(p), (pat, p, v)


class TestPostingsCache:
    def _fresh(self, **kw):
        return PostingsListCache(scope=instrument.Scope(), **kw)

    def test_hits_return_identical_arrays(self):
        rng = np.random.default_rng(45)
        seg = ImmutableSegment.from_mutable(
            (lambda m: (m.insert_batch([_rand_doc(rng, i)
                                        for i in range(30)]), m)[1])(
                MutableSegment()))
        cache = self._fresh()
        queries = [iq.new_term(b"f0", b"a"), iq.new_regexp(b"f1", b"a.*"),
                   iq.new_regexp(b"f2", b".*")]
        cold = [execute(seg, q, cache=cache) for q in queries]
        s0 = cache.stats()
        assert s0["misses"] >= len(queries) and s0["hits"] == 0
        warm = [execute(seg, q, cache=cache) for q in queries]
        s1 = cache.stats()
        assert s1["hits"] >= len(queries)
        assert s1["misses"] == s0["misses"]
        for c, w in zip(cold, warm):
            assert np.array_equal(c, w)
        # the cached leaf array is frozen: callers cannot corrupt it
        leaf = cache.get(seg.gen, b"f0", "term", b"a")
        if leaf is not None and len(leaf):
            with pytest.raises(ValueError):
                leaf[0] = 99

    def test_mutable_segments_bypass_cache(self):
        seg = MutableSegment()
        seg.insert(Document(b"d", ((b"f0", b"a"),)))
        cache = self._fresh()
        execute(seg, iq.new_term(b"f0", b"a"), cache=cache)
        s = cache.stats()
        assert s["hits"] == 0 and s["misses"] == 0 and s["size"] == 0

    def test_lru_capacity_evicts(self):
        cache = self._fresh(capacity=4)
        for i in range(10):
            cache.put(1, b"f", "term", b"k%d" % i, np.arange(i, dtype=np.int32))
        st = cache.stats()
        assert st["size"] == 4 and st["evictions"] == 6
        assert cache.get(1, b"f", "term", b"k0") is None
        assert cache.get(1, b"f", "term", b"k9") is not None

    def test_buffer_keys_normalized_at_boundary(self):
        cache = self._fresh()
        arr = np.arange(3, dtype=np.int32)
        field = bytearray(b"fld")
        key = bytearray(b"val")
        cache.put(1, field, "term", key, arr)
        field[0] = ord(b"X")  # mutating the caller's buffer...
        key[0] = ord(b"X")
        got = cache.get(1, b"fld", "term", b"val")  # ...must not move the key
        assert got is not None and np.array_equal(got, arr)
        assert cache.get(1, memoryview(b"fld"), "term",
                         memoryview(b"val")) is not None

    def test_invalidation_on_seal_and_merge(self):
        nsi = NamespaceIndex(block_size_ns=4 * xtime.HOUR)
        nsi.insert(b"s1", {b"host": b"a"}, T0)
        nsi.insert(b"s2", {b"host": b"b"}, T0)
        q = iq.new_term(b"host", b"a")
        assert nsi.query(q) == [b"s1"]
        assert nsi.query(q) == [b"s1"]  # warm: hits the snapshot's entries
        pre = nsi.postings_cache_stats()
        assert pre["size"] > 0
        # Seal drops the snapshot segment -> its entries are purged.
        nsi.tick(T0 + 5 * xtime.HOUR, retention_ns=30 * xtime.DAY)
        st = nsi.postings_cache_stats()
        assert st["invalidations"] >= 1
        assert nsi.query(q) == [b"s1"]  # re-resolved against the sealed seg
        # A second sealed block forces a merge on the next seal; merged-away
        # segment generations are invalidated too.
        blk = next(iter(nsi.blocks.values()))
        gens_before = [s.gen for s in blk.immutable]
        nsi.insert(b"s3", {b"host": b"a"}, T0)
        nsi.query(q)
        blk.seal()
        assert all(g != blk.immutable[0].gen for g in gens_before)
        assert nsi.query(q) == [b"s1", b"s3"]

    def test_put_after_invalidation_refused(self):
        """A query racing a seal outside the index lock must not
        repopulate entries for a dropped segment generation."""
        cache = self._fresh()
        arr = np.arange(3, dtype=np.int32)
        cache.put(7, b"f", "term", b"k", arr)
        cache.invalidate_segment(7)
        got = cache.put(7, b"f", "term", b"k", arr)  # late straggler
        assert np.array_equal(got, arr)  # caller still gets its array...
        assert cache.get(7, b"f", "term", b"k") is None  # ...but no entry
        assert cache.stats()["size"] == 0

    def test_expiry_invalidates(self):
        nsi = NamespaceIndex(block_size_ns=4 * xtime.HOUR)
        nsi.insert(b"s1", {b"host": b"a"}, T0)
        nsi.tick(T0 + 5 * xtime.HOUR, retention_ns=30 * xtime.DAY)
        assert nsi.query(iq.new_term(b"host", b"a")) == [b"s1"]
        assert len(nsi.postings_cache) > 0
        nsi.tick(T0 + 40 * xtime.DAY, retention_ns=30 * xtime.DAY)
        assert len(nsi.postings_cache) == 0
        assert nsi.query(iq.new_term(b"host", b"a")) == []

    def test_cold_and_warm_namespace_results_identical(self):
        rng = np.random.default_rng(46)
        nsi = NamespaceIndex(block_size_ns=4 * xtime.HOUR)
        for i in range(200):
            nsi.insert(b"id-%04d" % i,
                       {b"f0": _rand_value(rng), b"f1": _rand_value(rng)},
                       T0)
        nsi.tick(T0 + 5 * xtime.HOUR, retention_ns=30 * xtime.DAY)
        for seed in range(40):
            q = _rand_query(np.random.default_rng(5000 + seed))
            cold = nsi.query(q)
            warm = nsi.query(q)
            assert cold == warm, f"seed={5000 + seed}"
