"""Temporal kernel tests: batched device output vs a scalar Prometheus-
semantics oracle (the algorithms in promql's extrapolatedRate /
linearRegression / holt_winters, which the reference's
src/query/functions/temporal package follows)."""

import math

import numpy as np
import pytest

from m3_tpu.ops import temporal

S = 1_000_000_000
STEP_NS = 10 * S
STEP_S = 10.0


def oracle_extrapolated(win_vals, win_times, window_start, window_end,
                        is_counter, is_rate, range_s):
    """Scalar port of promql extrapolatedRate over one window's samples."""
    samples = [(t, v) for t, v in zip(win_times, win_vals) if not math.isnan(v)]
    if len(samples) < 2:
        return math.nan
    t_first, v_first = samples[0]
    t_last, v_last = samples[-1]
    increase = v_last - v_first
    if is_counter:
        prev = v_first
        for t, v in samples[1:]:
            if v < prev:
                increase += prev
            prev = v
    dur_start = t_first - window_start
    dur_end = window_end - t_last
    sampled = t_last - t_first
    if sampled == 0:
        return math.nan
    avg = sampled / (len(samples) - 1)
    threshold = avg * 1.1
    if is_counter and increase > 0 and v_first >= 0:
        dur_zero = sampled * (v_first / increase)
        dur_start = min(dur_start, dur_zero)
    extrap = sampled
    extrap += dur_start if dur_start < threshold else avg / 2
    extrap += dur_end if dur_end < threshold else avg / 2
    out = increase * (extrap / sampled)
    return out / range_s if is_rate else out


def make_grid(rng, n_series=7, n_cells=40, nan_frac=0.2, counter=True,
              scale=1.0, offset=0.0):
    if counter:
        inc = rng.exponential(5.0 * scale, size=(n_series, n_cells))
        vals = np.cumsum(inc, axis=1) + offset
        # Inject counter resets in some series.
        for i in range(0, n_series, 3):
            vals[i, n_cells // 2:] = np.cumsum(inc[i, n_cells // 2:])
    else:
        vals = rng.normal(offset, 10 * scale, size=(n_series, n_cells))
    mask = rng.random((n_series, n_cells)) < nan_frac
    vals[mask] = np.nan
    return vals


def window_times(T_ext, W, t):
    """Sample times (s) of window ending at output step t; grid cell j is
    time (j - (W-1)) * STEP_S relative to the first output step."""
    return [(t + w - (W - 1)) * STEP_S for w in range(W)]


@pytest.mark.parametrize("fn,is_counter,is_rate", [
    (temporal.rate, True, True),
    (temporal.increase, True, False),
    (temporal.delta, False, False),
])
def test_rate_family_matches_oracle(rng, fn, is_counter, is_rate):
    W = 6
    range_ns = W * STEP_NS
    grid = make_grid(rng, counter=is_counter, offset=1e9 if is_counter else 50.0)
    out = fn(grid, W, STEP_NS, range_ns)
    T_out = grid.shape[1] - W + 1
    assert out.shape == (grid.shape[0], T_out)
    for s in range(grid.shape[0]):
        for t in range(T_out):
            times = window_times(grid.shape[1], W, t)
            window_end = times[-1]
            window_start = window_end - W * STEP_S
            exp = oracle_extrapolated(
                grid[s, t:t + W], times, window_start, window_end,
                is_counter, is_rate, W * STEP_S)
            got = out[s, t]
            if math.isnan(exp):
                assert math.isnan(got), (s, t, got)
            else:
                # f32 residual math: exact in residual space, so compare to
                # the oracle run on the same f64 inputs with loose-ish rtol.
                assert got == pytest.approx(exp, rel=2e-4, abs=1e-3), (s, t)


def test_rate_counter_reset_handled(rng):
    W = 4
    grid = np.array([[0.0, 10.0, 20.0, 5.0, 15.0, 25.0]])
    out = temporal.increase(grid, W, STEP_NS, W * STEP_NS)
    # Window covering the reset must add the pre-reset value (20).
    times = window_times(6, W, 2)
    exp = oracle_extrapolated(grid[0, 2:6], times, times[-1] - W * STEP_S,
                              times[-1], True, False, W * STEP_S)
    assert out[0, 2] == pytest.approx(exp, rel=1e-6)
    assert exp > 20  # reset correction kicked in


@pytest.mark.parametrize("kind,np_fn", [
    ("sum", np.nansum), ("min", np.nanmin), ("max", np.nanmax),
    ("avg", np.nanmean),
])
def test_over_time_matches_numpy(rng, kind, np_fn):
    W = 5
    grid = make_grid(rng, counter=False, offset=1e8)  # large offset: f64 path
    out = temporal.over_time(grid, W, kind)
    for s in range(grid.shape[0]):
        for t in range(out.shape[1]):
            win = grid[s, t:t + W]
            if np.all(np.isnan(win)):
                assert math.isnan(out[s, t])
            else:
                assert out[s, t] == pytest.approx(np_fn(win), rel=1e-6), (s, t)


def test_stddev_over_time_large_offset_precision(rng):
    """The f64-baseline split must survive mean >> stddev (the classic f32
    catastrophic cancellation case)."""
    W = 8
    base = 1e9
    grid = base + rng.normal(0, 1.0, size=(3, 30))
    out = temporal.over_time(grid, W, "stddev")
    for s in range(3):
        for t in range(out.shape[1]):
            win = grid[s, t:t + W]
            assert out[s, t] == pytest.approx(np.std(win), rel=1e-3)


def test_count_and_present(rng):
    W = 4
    grid = make_grid(rng, counter=False, nan_frac=0.5)
    cnt = temporal.over_time(grid, W, "count")
    pres = temporal.over_time(grid, W, "present")
    for s in range(grid.shape[0]):
        for t in range(cnt.shape[1]):
            n = np.isfinite(grid[s, t:t + W]).sum()
            if n == 0:
                assert math.isnan(cnt[s, t]) and math.isnan(pres[s, t])
            else:
                assert cnt[s, t] == n and pres[s, t] == 1.0


def test_quantile_over_time_exact_values(rng):
    W = 6
    grid = make_grid(rng, counter=False, nan_frac=0.3, offset=1e7)
    out = temporal.quantile_over_time(grid, W, 0.5)
    for s in range(grid.shape[0]):
        for t in range(out.shape[1]):
            win = grid[s, t:t + W]
            vals = np.sort(win[np.isfinite(win)])
            if vals.size == 0:
                assert math.isnan(out[s, t])
                continue
            pos = 0.5 * (vals.size - 1)
            lo, hi = int(np.floor(pos)), min(int(np.floor(pos)) + 1, vals.size - 1)
            exp = vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)
            assert out[s, t] == pytest.approx(exp, rel=1e-9), (s, t)


def test_irate_idelta(rng):
    W = 5
    grid = make_grid(rng, counter=True, nan_frac=0.3)
    out_ir = temporal.irate(grid, W, STEP_NS)
    out_id = temporal.idelta(grid, W, STEP_NS)
    for s in range(grid.shape[0]):
        for t in range(out_ir.shape[1]):
            win = grid[s, t:t + W]
            valid = np.flatnonzero(np.isfinite(win))
            if valid.size < 2:
                assert math.isnan(out_ir[s, t])
                continue
            i2, i1 = valid[-1], valid[-2]
            dv, dt = win[i2] - win[i1], (i2 - i1) * STEP_S
            exp_ir = (win[i2] if win[i2] < win[i1] else dv) / dt
            assert out_ir[s, t] == pytest.approx(exp_ir, rel=1e-4, abs=1e-6)
            assert out_id[s, t] == pytest.approx(dv, rel=1e-4, abs=1e-3)


def test_changes_resets():
    grid = np.array([[1.0, 1.0, 2.0, np.nan, 2.0, 1.0, 3.0]])
    W = 7
    ch = temporal.changes(grid, W)
    rs = temporal.resets(grid, W)
    # changes: 1->2 (yes), 2->2 across NaN (no), 2->1 (yes), 1->3 (yes)
    assert ch[0, 0] == 3
    assert rs[0, 0] == 1  # only 2->1


def test_deriv_predict_linear(rng):
    W = 8
    slope_true = 2.5
    t = np.arange(30) * STEP_S
    grid = 1e6 + slope_true * t[None, :] + rng.normal(0, 0.01, size=(2, 30))
    d = temporal.deriv(grid, W, STEP_NS)
    p = temporal.predict_linear(grid, W, STEP_NS, 60.0)
    for s in range(2):
        for i in range(d.shape[1]):
            assert d[s, i] == pytest.approx(slope_true, rel=1e-3)
            t_now = (i + W - 1) * STEP_S
            exp = 1e6 + slope_true * (t_now + 60.0)
            assert p[s, i] == pytest.approx(exp, rel=1e-6)


def test_holt_winters_matches_scalar(rng):
    W = 10
    sf, tf = 0.3, 0.6
    grid = make_grid(rng, counter=False, nan_frac=0.2, offset=100.0)
    out = temporal.holt_winters(grid, W, sf, tf)

    def scalar_hw(win):
        vals = [v for v in win if not math.isnan(v)]
        if len(vals) < 2:
            return math.nan
        s_prev, b_prev = vals[0], vals[1] - vals[0]
        # promql: s0=v0, b0=v1-v0, then smooth from the second sample on.
        for x in vals[1:]:
            s1 = sf * x + (1 - sf) * (s_prev + b_prev)
            b_prev = tf * (s1 - s_prev) + (1 - tf) * b_prev
            s_prev = s1
        return s_prev

    for s in range(grid.shape[0]):
        for t in range(out.shape[1]):
            exp = scalar_hw(grid[s, t:t + W])
            if math.isnan(exp):
                assert math.isnan(out[s, t])
            else:
                assert out[s, t] == pytest.approx(exp, rel=1e-3, abs=1e-3), (s, t)


def test_rate_no_cancellation_on_huge_counter():
    """A quiet window late in a high-total counter grid must not lose its
    tiny increase to f32 accumulation error (the windowed sums accumulate
    per window, never as a global running prefix)."""
    T, W = 139, 30
    # Busy prefix pushes the counter to ~1e13, then a quiet tail adds 1/step.
    busy = np.full(60, 2e11)
    quiet = np.full(T - 61, 1.0)
    increments = np.concatenate([[0.0], busy, quiet])
    grid = np.cumsum(increments)[None, :]
    out = temporal.increase(grid, W, STEP_NS, W * STEP_NS)
    # Last window covers only quiet cells: true increase = W-1 samples * 1.
    expected = (W - 1) * 1.0 * (W / (W - 1))  # extrapolated to full range
    assert out[0, -1] == pytest.approx(expected, rel=1e-3)
    assert (out[0, -5:] > 0).all()  # counter increase can never go negative


class TestPallasWindow:
    """Parity of the opt-in Pallas strided-window kernel (M3_TPU_PALLAS=1)
    against the XLA reduce_window path — same masked-by-finiteness
    semantics, m2 in the same two-pass form, empty windows included."""

    def test_kernel_parity_all_stats_strides(self):
        import jax.numpy as jnp

        from m3_tpu.ops import pallas_window as pw
        from m3_tpu.ops import temporal

        rng = np.random.default_rng(3)
        S, K, W = 13, 67, 6
        resid = rng.standard_normal((S, K)).astype(np.float32)
        resid[rng.random((S, K)) < 0.2] = np.nan
        resid[0] = np.nan  # one fully-empty series
        for stride in (1, 2, 3):
            for stat in pw.STATS:
                got_s, got_c = pw.window_stat(jnp.asarray(resid), W, stride, stat)
                ref_s, ref_c = temporal._window_stat(jnp.asarray(resid), W, stat)
                got_s, got_c = np.asarray(got_s), np.asarray(got_c)
                ref_s = np.asarray(ref_s)[:, ::stride]
                ref_c = np.asarray(ref_c)[:, ::stride].astype(np.float32)
                np.testing.assert_array_equal(got_c, ref_c)
                # The contract covers populated windows only: both callers
                # mask count==0 to NaN, and the raw empty-window planes
                # legitimately differ ('last': 0.0 vs the XLA gather's
                # clipped-index artifact).
                pop = ref_c > 0
                np.testing.assert_allclose(
                    got_s[pop], ref_s[pop],
                    rtol=1e-6, atol=1e-6, err_msg=f"{stat} stride={stride}")

    def test_empty_window_counts_zero(self):
        import jax.numpy as jnp

        from m3_tpu.ops import pallas_window as pw

        # crafted fully-NaN window inside a row whose column 0 is finite
        # (the case the XLA raw plane renders differently)
        resid = np.array([[5.0, 1.0, np.nan, np.nan, np.nan, 2.0, 3.0, 4.0]],
                         np.float32)
        got_s, got_c = pw.window_stat(jnp.asarray(resid), 3, 1, "last")
        got_s, got_c = np.asarray(got_s), np.asarray(got_c)
        assert got_c[0, 2] == 0.0
        assert got_s[0, 2] == 0.0  # documented empty-window value

    def test_over_time_dispatch(self, monkeypatch):
        from m3_tpu.ops import temporal

        rng = np.random.default_rng(5)
        grid = np.cumsum(rng.poisson(3.0, (9, 50)), axis=1).astype(np.float64)
        grid[rng.random((9, 50)) < 0.1] = np.nan
        refs = {k: temporal.over_time(grid, 5, k, stride=2)
                for k in ("sum", "avg", "min", "max", "count", "last",
                          "stddev", "stdvar")}
        monkeypatch.setattr(temporal, "_use_pallas", lambda: True)
        temporal._over_time_fn.cache_clear()
        temporal._over_time_finish_fn.cache_clear()
        try:
            for k, ref in refs.items():
                got = temporal.over_time(grid, 5, k, stride=2)
                np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-9,
                                           equal_nan=True, err_msg=k)
                got_dev = temporal.over_time(grid, 5, k, stride=2,
                                             finish="device")
                np.testing.assert_allclose(got_dev, ref, rtol=1e-5, atol=1e-5,
                                           equal_nan=True, err_msg=k + " device")
        finally:
            temporal._over_time_fn.cache_clear()
            temporal._over_time_finish_fn.cache_clear()

    def test_narrow_grid_falls_back(self, monkeypatch):
        # K < W: the dispatch must use the XLA empty plane, not a
        # zero/negative-width pallas grid.
        from m3_tpu.ops import temporal

        monkeypatch.setattr(temporal, "_use_pallas", lambda: True)
        resid = np.full((4, 3), 1.0, np.float32)
        out, cnt = temporal._window_stat_strided(resid, 6, "sum", 1)
        assert out.shape == (4, 0) and cnt.shape == (4, 0)

    def test_oversized_unroll_falls_back(self, monkeypatch):
        # The kernel statically unrolls T_out window reductions (Mosaic
        # alignment constraint); past MAX_UNROLL_STEPS the dispatch must
        # take the constant-program-size XLA path instead of tracing a
        # pathological kernel — and window_stat itself must refuse.
        from m3_tpu.ops import pallas_window as pw

        monkeypatch.setattr(temporal, "_use_pallas", lambda: True)
        K = pw.MAX_UNROLL_STEPS + 40  # stride 1, W 6 -> T_out > cap
        resid = np.ones((4, K), np.float32)
        out, cnt = temporal._window_stat_strided(resid, 6, "sum", 1)
        assert out.shape == (4, K - 5)  # XLA path served it
        assert float(np.asarray(out)[0, 0]) == 6.0
        with pytest.raises(ValueError, match="MAX_UNROLL_STEPS"):
            pw.window_stat(resid, 6, 1, "sum")
