"""Overload-protection tests: query limits (sliding windows + enforcer
parent/child budgets), ingest admission control with priority shedding,
the degradation state machine, typed ResourceExhausted over the wire,
and the seeded open-loop load generator (reference test model:
src/dbnode/storage/limits/query_limits_test.go + x/cost enforcer tests;
shedding discipline per "The Tail at Scale" / DAGOR)."""

import socket
import threading
import time

import numpy as np
import pytest

from m3_tpu.utils import limits as xlimits
from m3_tpu.utils.cost import CostLimitExceeded, Enforcer
from m3_tpu.utils.health import (
    DEGRADED,
    OK,
    SHEDDING,
    AdmissionGate,
    HealthTracker,
    Priority,
)
from m3_tpu.utils.limits import (
    Backpressure,
    LimitOptions,
    QueryLimits,
    ResourceExhausted,
    SlidingWindow,
)
from m3_tpu.utils.retry import DeadlineExceeded, default_is_retryable

NS = b"t"


@pytest.fixture(autouse=True)
def _isolated_global_limits():
    """Every test sees a fresh (unlimited) global registry; the previous
    one is restored so this suite cannot leak limits into other files."""
    prev = xlimits.set_global(xlimits.QueryLimits())
    yield
    xlimits.set_global(prev)


# ------------------------------------------------------------- cost enforcer


class TestEnforcerRelease:
    def test_release_none_credits_parent(self):
        """THE regression: release(None) zeroed the child but never
        credited the parent, permanently leaking global budget per
        completed query (pre-fix, parent.current() stayed 30 here)."""
        parent = Enforcer(limit=100, name="global")
        child = parent.child(50, name="query")
        child.add(30)
        assert parent.current() == 30
        child.release(None)
        assert child.current() == 0
        assert parent.current() == 0, "release(None) leaked the parent budget"

    def test_release_none_after_partial_release(self):
        parent = Enforcer(limit=100)
        child = parent.child(50)
        child.add(40)
        child.release(15)
        assert parent.current() == 25
        child.release(None)  # remaining 25
        assert child.current() == 0 and parent.current() == 0

    def test_explicit_release_unchanged(self):
        parent = Enforcer(limit=100)
        child = parent.child(50)
        child.add(10)
        child.release(10)
        assert child.current() == 0 and parent.current() == 0

    def test_release_none_through_grandparent_chain(self):
        grand = Enforcer(limit=1000)
        parent = grand.child(100)
        child = parent.child(50)
        child.add(20)
        assert grand.current() == 20
        child.release(None)
        assert (child.current(), parent.current(), grand.current()) == (0, 0, 0)

    def test_rejected_add_rolls_back_every_level(self):
        parent = Enforcer(limit=25)
        child = parent.child(None)
        child.add(20)
        with pytest.raises(CostLimitExceeded):
            child.add(10)  # parent rejects
        assert child.current() == 20 and parent.current() == 20


class TestEnforcerConcurrency:
    def test_sixteen_thread_hammer_never_negative_never_leaks(self):
        """16 threads interleave add/release on children of one parent:
        current() must never go negative mid-flight and must settle at
        exactly zero (no lost or doubled credit)."""
        parent = Enforcer(limit=None, name="global")
        negatives = []
        errors = []

        def hammer(i):
            child = parent.child(None, name=f"w{i}")
            try:
                for _ in range(500):
                    child.add(3)
                    if parent.current() < 0 or child.current() < 0:
                        negatives.append(i)
                    child.release(1)
                    child.release(None)  # the remaining 2
            except Exception as e:  # noqa: BLE001 — surface in main thread
                errors.append(e)

        threads = [threading.Thread(target=hammer, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert not negatives, "current() observed negative under contention"
        assert parent.current() == 0

    def test_limited_parent_contention_settles_zero(self):
        parent = Enforcer(limit=48, name="global")

        def worker():
            child = parent.child(None)
            for _ in range(300):
                try:
                    child.add(2)
                except CostLimitExceeded:
                    continue
                child.release(None)

        threads = [threading.Thread(target=worker) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert parent.current() == 0


# ----------------------------------------------------------- sliding windows


class TestSlidingWindow:
    def test_exact_expiry_after_idle_second(self):
        """Saturate, idle one window, and the whole budget must be back:
        no stuck saturation (the property the reference gets from its
        per-second reset ticker)."""
        t = [0.0]
        w = SlidingWindow(100, clock=lambda: t[0])
        assert w.try_charge(100)
        assert not w.try_charge(1)
        t[0] = 1.0001
        assert w.current() == 0
        assert w.try_charge(100)

    def test_buckets_expire_individually(self):
        t = [0.0]
        w = SlidingWindow(100, buckets=10, clock=lambda: t[0])
        w.try_charge(60)
        t[0] = 0.5
        w.try_charge(40)
        assert not w.try_charge(1)
        # the first bucket (60) leaves the window before the second does
        t[0] = 1.05
        assert w.current() == 40
        assert w.try_charge(60)
        assert not w.try_charge(1)

    def test_refused_charge_consumes_nothing(self):
        t = [0.0]
        w = SlidingWindow(10, clock=lambda: t[0])
        w.try_charge(8)
        assert not w.try_charge(5)
        assert w.current() == 8
        assert w.try_charge(2)

    def test_property_window_sum_matches_reference(self):
        """Seeded random charge/advance sequence: the window total must
        equal a brute-force sum of charges inside the trailing window
        (quantized to bucket granularity) at every step."""
        import random

        rng = random.Random(1234)
        t = [0.0]
        w = SlidingWindow(10_000, buckets=10, clock=lambda: t[0])
        accepted = []  # (time, n)
        bucket_s = w.window_s / 10
        for _ in range(500):
            t[0] += rng.random() * 0.3
            n = rng.randint(1, 400)
            if w.try_charge(n):
                accepted.append((t[0], n))
            now_bucket = int(t[0] / bucket_s)
            floor = (now_bucket - 10 + 1) * bucket_s
            expect = sum(x for ts, x in accepted
                         if int(ts / bucket_s) * bucket_s >= floor)
            assert w.current() == expect

    def test_rejects_nonpositive_limit(self):
        with pytest.raises(ValueError):
            SlidingWindow(0)


# -------------------------------------------------------------- query limits


class TestQueryLimits:
    def test_scope_releases_concurrent_budget(self):
        ql = QueryLimits(docs_matched=LimitOptions(concurrent=100))
        with ql.scope("q") as s:
            s.charge("docs_matched", 60)
            assert ql.enforcer("docs_matched").current() == 60
        assert ql.enforcer("docs_matched").current() == 0

    def test_per_query_cap_spares_the_process(self):
        ql = QueryLimits(docs_matched=LimitOptions(concurrent=1000,
                                                   per_query=50))
        with ql.scope("greedy") as s:
            with pytest.raises(ResourceExhausted):
                s.charge("docs_matched", 51)
            s.charge("docs_matched", 50)  # within the per-query cap
        assert ql.enforcer("docs_matched").current() == 0

    def test_thousand_rejected_queries_leak_nothing(self):
        """The acceptance bar: budget fully released after 1k rejected
        queries (every add that raised was rolled back; every scope exit
        credited the chain)."""
        ql = QueryLimits(series_fetched=LimitOptions(concurrent=10))
        for _ in range(1000):
            with pytest.raises(ResourceExhausted):
                with ql.scope("q") as s:
                    s.charge("series_fetched", 5)
                    s.charge("series_fetched", 50)  # rejected
        assert ql.enforcer("series_fetched").current() == 0

    def test_enforcer_rejection_leaves_no_phantom_window_load(self):
        """A charge the enforcer rejects must not consume window budget:
        a retry storm of rejected queries cannot poison the next second
        for unrelated queries."""
        t = [0.0]
        ql = QueryLimits(clock=lambda: t[0],
                         docs_matched=LimitOptions(per_second=1000,
                                                   concurrent=10))
        for _ in range(100):
            with pytest.raises(ResourceExhausted):
                with ql.scope("q") as s:
                    s.charge("docs_matched", 50)  # enforcer rejects (>10)
        with ql.scope("ok") as s:
            s.charge("docs_matched", 10)  # window must be pristine
        lim = ql._limits["docs_matched"]
        assert lim.window.current() == 10

    def test_window_rejection_releases_enforcer_charge(self):
        ql = QueryLimits(docs_matched=LimitOptions(per_second=5,
                                                   concurrent=1000))
        with ql.scope("q") as s:
            with pytest.raises(ResourceExhausted):
                s.charge("docs_matched", 50)  # window rejects
            assert s.current("docs_matched") == 0
        assert ql.enforcer("docs_matched").current() == 0

    def test_window_shared_across_scopes(self):
        t = [0.0]
        ql = QueryLimits(clock=lambda: t[0],
                         docs_matched=LimitOptions(per_second=100))
        with ql.scope("a") as s:
            s.charge("docs_matched", 80)
        with ql.scope("b") as s:
            with pytest.raises(ResourceExhausted):
                s.charge("docs_matched", 30)
        t[0] = 1.1
        with ql.scope("c") as s:
            s.charge("docs_matched", 100)

    def test_module_charge_routes_to_installed_scope(self):
        ql = QueryLimits(bytes_read=LimitOptions(concurrent=100))
        with ql.scope("q"):
            xlimits.charge("bytes_read", 40)
            assert ql.enforcer("bytes_read").current() == 40
        assert ql.enforcer("bytes_read").current() == 0

    def test_scopeless_charge_hits_global_window(self):
        t = [0.0]
        prev = xlimits.set_global(QueryLimits(
            clock=lambda: t[0],
            series_fetched=LimitOptions(per_second=10)))
        try:
            xlimits.charge("series_fetched", 10)
            with pytest.raises(ResourceExhausted):
                xlimits.charge("series_fetched", 1)
        finally:
            xlimits.set_global(prev)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            QueryLimits(bogus=LimitOptions(per_second=1))

    def test_saturation_tracks_in_flight(self):
        ql = QueryLimits(datapoints_decoded=LimitOptions(concurrent=100))
        assert ql.saturation() == 0.0
        with ql.scope("q") as s:
            s.charge("datapoints_decoded", 80)
            assert ql.saturation() == pytest.approx(0.8)
        assert ql.saturation() == 0.0

    def test_resource_exhausted_is_retryable_deadline_is_not(self):
        assert default_is_retryable(ResourceExhausted("shed"))
        assert default_is_retryable(Backpressure("shed"))
        assert not default_is_retryable(DeadlineExceeded("late"))


# ---------------------------------------------------------- admission gating


class TestAdmissionGate:
    def _gate(self, capacity=4, high=0.5):
        return AdmissionGate(capacity, high_watermark=high,
                             tracker=HealthTracker())

    def test_watermark_shed_order(self):
        g = self._gate()  # capacity 4, high watermark 2
        assert g.try_admit(2, Priority.BULK)
        assert not g.try_admit(1, Priority.BULK)      # past high: bulk shed
        assert g.try_admit(2, Priority.NORMAL)        # up to capacity
        assert not g.try_admit(1, Priority.NORMAL)    # at capacity: shed
        assert g.try_admit(1, Priority.CRITICAL)      # never shed
        assert g.depth() == 5
        assert g.shed == {"critical": 0, "normal": 1, "bulk": 1}

    def test_release_restores_admission(self):
        g = self._gate()
        g.admit(4, Priority.NORMAL)
        with pytest.raises(Backpressure):
            g.admit(1, Priority.NORMAL)
        g.release(3)
        g.admit(1, Priority.BULK)  # depth 2 == high watermark again
        assert g.depth() == 2

    def test_held_releases_on_exception(self):
        g = self._gate()
        with pytest.raises(RuntimeError):
            with g.held(2, Priority.NORMAL):
                assert g.depth() == 2
                raise RuntimeError("boom")
        assert g.depth() == 0

    def test_max_depth_records_high_water(self):
        g = self._gate()
        with g.held(3):
            pass
        assert g.depth() == 0 and g.max_depth() == 3

    def test_rejects_nonpositive_capacity(self):
        with pytest.raises(ValueError):
            AdmissionGate(0, tracker=HealthTracker())

    def test_oversized_request_admitted_when_idle(self):
        """Semaphore convention: one request larger than the whole budget
        runs ALONE on an idle gate — otherwise an oversized batch frame
        would be deterministically shed forever."""
        g = self._gate(capacity=4)
        assert g.try_admit(100, Priority.NORMAL)   # idle: runs alone
        assert not g.try_admit(1, Priority.NORMAL)  # but nothing joins it
        g.release(100)
        g.admit(1, Priority.NORMAL)
        assert not g.try_admit(100, Priority.BULK)  # busy: oversized sheds


class TestHealthTracker:
    def test_transitions_with_hysteresis(self):
        sat = [0.0]
        tr = HealthTracker(degraded_at=0.7, shedding_at=0.95,
                           recover_margin=0.1)
        tr.register("src", lambda: sat[0])
        assert tr.evaluate() == OK
        sat[0] = 0.75
        assert tr.evaluate() == DEGRADED
        sat[0] = 0.96
        assert tr.evaluate() == SHEDDING
        # hysteresis: just below the threshold is NOT enough to recover
        sat[0] = 0.90
        assert tr.evaluate() == SHEDDING
        sat[0] = 0.80
        assert tr.evaluate() == DEGRADED
        sat[0] = 0.65
        assert tr.evaluate() == DEGRADED  # within recover margin of 0.7
        sat[0] = 0.3
        assert tr.evaluate() == OK
        states = [(old, new) for old, new, _ in tr.transitions]
        assert states == [(OK, DEGRADED), (DEGRADED, SHEDDING),
                          (SHEDDING, DEGRADED), (DEGRADED, OK)]

    def test_dead_probe_reads_saturated(self):
        tr = HealthTracker()

        def boom():
            raise RuntimeError("probe died")

        tr.register("dead", boom)
        assert tr.evaluate() == SHEDDING

    def test_gate_feeds_tracker(self):
        tr = HealthTracker(degraded_at=0.5, shedding_at=0.9)
        g = AdmissionGate(10, name="gate-under-test", tracker=tr)
        assert tr.evaluate() == OK
        g.admit(6)
        assert tr.evaluate() == DEGRADED
        g.admit(3, Priority.CRITICAL)
        assert tr.evaluate() == SHEDDING
        g.release(9)
        assert tr.evaluate() == OK


# ----------------------------------------------------- charge-site threading


def _make_db(n_series=20):
    from m3_tpu.parallel.sharding import ShardSet
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.namespace import NamespaceOptions

    db = Database(ShardSet(2), clock=lambda: 10**9)
    db.mark_bootstrapped()
    db.ensure_namespace(NS, NamespaceOptions(index_enabled=True,
                                             writes_to_commitlog=False))
    for i in range(n_series):
        db.write(NS, b"s-%03d" % i, 10**6 * i, float(i),
                 tags={b"__name__": b"m", b"host": b"h%03d" % i})
    return db


class TestChargeSites:
    def test_index_query_charges_docs_matched_before_materialization(self):
        from m3_tpu.index import query as iq

        db = _make_db(30)
        t = [0.0]
        xlimits.set_global(QueryLimits(
            clock=lambda: t[0],
            docs_matched=LimitOptions(per_second=50)))
        assert len(db.query_ids(NS, iq.AllQuery())) == 30
        with pytest.raises(ResourceExhausted):
            db.query_ids(NS, iq.AllQuery())  # 30 + 30 > 50 within a second
        t[0] = 1.1  # window expired: the same query passes again
        assert len(db.query_ids(NS, iq.AllQuery())) == 30

    def test_database_read_charges_datapoints(self):
        db = _make_db(5)
        xlimits.set_global(QueryLimits(
            datapoints_decoded=LimitOptions(per_second=3)))
        db.read(NS, b"s-000", 0, 2**62)  # 1 point: fits
        db.read(NS, b"s-001", 0, 2**62)
        db.read(NS, b"s-002", 0, 2**62)
        with pytest.raises(ResourceExhausted):
            db.read(NS, b"s-003", 0, 2**62)

    def test_query_ids_charges_series_fetched(self):
        from m3_tpu.index import query as iq

        db = _make_db(8)
        xlimits.set_global(QueryLimits(
            series_fetched=LimitOptions(per_second=5)))
        with pytest.raises(ResourceExhausted):
            db.query_ids(NS, iq.AllQuery())

    def test_executor_per_query_datapoint_budget(self):
        from m3_tpu.query.executor import Engine

        class Big:
            def fetch_raw(self, matchers, s, e):
                t = np.arange(50, dtype=np.int64) * 10**9
                return {b"a": {"tags": {b"__name__": b"m"},
                               "t": t, "v": np.ones(50)}}

        ql = QueryLimits(datapoints_decoded=LimitOptions(concurrent=1000,
                                                         per_query=10))
        eng = Engine(Big(), query_limits=ql)
        with pytest.raises(ResourceExhausted):
            eng.execute_range("m", 0, 60 * 10**9, 15 * 10**9)
        assert ql.enforcer("datapoints_decoded").current() == 0, \
            "failed query leaked its datapoint budget"


# ------------------------------------------------------------- wire round-trip


class TestWireRoundTrip:
    def _server(self, gate=None, limits=None, n_series=20):
        from m3_tpu.rpc import NodeServer, NodeService

        db = _make_db(n_series)
        svc = NodeService(db, gate=gate, limits=limits)
        return NodeServer(svc, port=0).start()

    def test_resource_exhausted_rides_the_wire_typed(self):
        from m3_tpu.client.session import HostClient
        from m3_tpu.index import query as iq
        from m3_tpu.rpc import wire
        from m3_tpu.utils.retry import RetryOptions

        srv = self._server(limits=QueryLimits(
            docs_matched=LimitOptions(per_second=5)), n_series=20)
        try:
            hc = HostClient(srv.endpoint, timeout=5,
                            retry_opts=RetryOptions(max_attempts=3,
                                                    initial_backoff_s=0.01,
                                                    seed=7))
            with pytest.raises(ResourceExhausted):
                hc.call("fetch_tagged", ns=NS,
                        query=wire.query_to_wire(iq.AllQuery()),
                        start_ns=0, end_ns=2**62)
            # classified retryable: the retrier burned every attempt
            assert hc.retrier.attempts == 3
            # the host answered every time: a shedding node must NOT trip
            # the breaker (that would dogpile its replicas)
            assert hc.breaker.state != "open"
            # the connection stayed synced and poolable: health works
            assert hc.call("health")["ok"]
            hc.close()
        finally:
            srv.close()

    def test_admission_shed_write_is_backpressure_but_health_passes(self):
        from m3_tpu.client.session import HostClient
        from m3_tpu.utils.retry import RetryOptions

        gate = AdmissionGate(2, high_watermark=0.5, tracker=HealthTracker())
        srv = self._server(gate=gate)
        try:
            gate.admit(2, Priority.CRITICAL)  # simulate a full node
            hc = HostClient(srv.endpoint, timeout=5,
                            retry_opts=RetryOptions(max_attempts=2,
                                                    initial_backoff_s=0.01,
                                                    seed=7))
            with pytest.raises(ResourceExhausted):
                hc.call("write", ns=NS, id=b"x", t_ns=0, value=1.0)
            # health and replication metadata are CRITICAL: never shed
            assert hc.call("health")["ok"]
            r = hc.call("fetch_blocks_metadata", ns=NS, shard=0,
                        start_ns=0, end_ns=2**62)
            assert "series" in r
            hc.close()
        finally:
            srv.close()

    def test_bulk_priority_hint_sheds_first(self):
        from m3_tpu.rpc.node_server import method_priority

        assert method_priority("write") == Priority.NORMAL
        assert method_priority("write", "bulk") == Priority.BULK
        assert method_priority("health", "bulk") == Priority.CRITICAL
        assert method_priority("fetch_blocks") == Priority.CRITICAL

    def test_deadline_still_not_retryable_alongside(self):
        """The two typed frames stay distinct: deadline never retries."""
        from m3_tpu.client.session import HostClient
        from m3_tpu.utils.retry import Deadline, RetryOptions

        srv = self._server()
        try:
            hc = HostClient(srv.endpoint, timeout=5,
                            retry_opts=RetryOptions(max_attempts=3,
                                                    initial_backoff_s=0.01,
                                                    seed=7))
            with pytest.raises(DeadlineExceeded):
                hc.call("health", _deadline=Deadline.after(-0.001))
            assert hc.retrier.attempts <= 1
            hc.close()
        finally:
            srv.close()


# ------------------------------------------------------------ ingest shedding


class TestCoordinatorIngest:
    def _writer(self, capacity=2):
        from m3_tpu.coordinator.ingest import DownsamplerAndWriter

        class Sink:
            def __init__(self):
                self.writes = []

            def write(self, sid, tags, t, v):
                self.writes.append(sid)

        sink = Sink()
        gate = AdmissionGate(capacity, high_watermark=0.5,
                             tracker=HealthTracker())
        return DownsamplerAndWriter(sink, gate=gate), sink, gate

    def test_sheds_by_priority_past_watermarks(self):
        w, sink, gate = self._writer(capacity=2)
        gate.admit(1, Priority.CRITICAL)  # depth 1 == high watermark
        with pytest.raises(Backpressure):
            w.write({b"__name__": b"m"}, 0, 1.0, priority=Priority.BULK)
        w.write({b"__name__": b"m"}, 0, 1.0)  # NORMAL still fits
        gate.admit(1, Priority.CRITICAL)      # now at capacity
        with pytest.raises(Backpressure):
            w.write({b"__name__": b"m"}, 0, 2.0)
        w.write({b"__name__": b"m"}, 0, 3.0, priority=Priority.CRITICAL)
        assert len(sink.writes) == 2
        assert gate.shed["bulk"] == 1 and gate.shed["normal"] == 1
        assert gate.shed["critical"] == 0

    def test_write_batch_admission_is_all_or_nothing(self):
        """A shed batch writes NOTHING: per-sample admission would leave
        a partially-written prefix that the 429-retrying producer then
        re-writes, double-counting it."""
        w, sink, gate = self._writer(capacity=4)
        gate.admit(2, Priority.CRITICAL)  # 3-sample batch can't fit
        samples = [({b"__name__": b"m"}, i, float(i)) for i in range(3)]
        with pytest.raises(Backpressure):
            w.write_batch(samples)
        assert sink.writes == []  # nothing partial
        gate.release(2)
        w.write_batch(samples)
        assert len(sink.writes) == 3
        assert gate.depth() == 0

    def test_m3msg_ingester_never_shed(self):
        from m3_tpu.coordinator.ingest import M3MsgIngester
        from m3_tpu.metrics import id as metric_id
        from m3_tpu.rpc import wire

        written = []

        class Sink:
            def write(self, sid, tags, t, v):
                written.append(sid)

        gate = AdmissionGate(1, tracker=HealthTracker())
        gate.admit(1, Priority.CRITICAL)  # saturated
        ing = M3MsgIngester(lambda pol: Sink(), gate=gate)
        payload = wire.encode({"id": metric_id.encode(b"cpu", {}),
                               "t": 123, "v": 4.5, "sp": "10s:2d"})
        ing(0, payload)  # must NOT raise: pipeline output is critical
        assert written and ing.ingested == 1
        assert gate.depth() == 1  # released its own admit


class TestRawTCPShedding:
    def _server(self, capacity=2):
        from m3_tpu.aggregator.server import RawTCPServer

        class StubAgg:
            def __init__(self):
                self.timed = []
                self.forwarded_received = 0

            def add_timed(self, mt, mid, t, v, pol, agg_id):
                self.timed.append(mid)

        agg = StubAgg()
        srv = RawTCPServer(agg, port=0,
                           gate=AdmissionGate(capacity, high_watermark=0.5,
                                              tracker=HealthTracker()))
        srv.start()  # close() blocks unless serve_forever is running
        return srv, agg

    def test_sheds_normal_counts_drop(self):
        srv, agg = self._server(capacity=2)
        try:
            entry = {"t": "timed", "mtype": 3, "id": b"x", "time": 0,
                     "value": 1.0, "policy": "10s:2d", "agg_id": 0}
            assert srv._handle(dict(entry)) == 1
            srv.gate.admit(2, Priority.CRITICAL)  # saturate
            assert srv._handle(dict(entry)) == 0
            assert srv.shed == 1 and srv.errors == 0
            assert len(agg.timed) == 1
        finally:
            srv.close()

    def test_bulk_marked_frames_shed_at_high_watermark(self):
        srv, agg = self._server(capacity=2)
        try:
            entry = {"t": "timed", "mtype": 3, "id": b"x", "time": 0,
                     "value": 1.0, "policy": "10s:2d", "agg_id": 0,
                     "pri": "bulk"}
            srv.gate.admit(1, Priority.CRITICAL)  # depth 1 == high
            assert srv._handle(dict(entry)) == 0
            assert srv.shed == 1
        finally:
            srv.close()


# ------------------------------------------------------------ msg backpressure


class TestProducerBackpressure:
    def _dead_endpoint(self):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return f"127.0.0.1:{port}"

    def test_publish_backpressure_at_high_watermark(self):
        from m3_tpu.cluster.placement import Instance, initial_placement
        from m3_tpu.msg import ConsumerService, Producer, Topic

        placement = initial_placement(
            [Instance(id="c0", endpoint=self._dead_endpoint())],
            num_shards=2, replica_factor=1)
        prod = Producer(Topic("t", 2, (ConsumerService("svc"),)),
                        {"svc": lambda: placement},
                        max_buffer_bytes=1000, high_watermark=0.5,
                        retry_delay_s=5.0)
        try:
            payload = b"x" * 100
            sent = 0
            with pytest.raises(Backpressure):
                for _ in range(50):
                    prod.publish(0, payload)
                    sent += 1
            # the watermark held BEFORE drop-oldest data loss kicked in
            assert prod.buffered_bytes() <= 500
            assert prod.dropped_oldest == 0
            assert prod.backpressure_rejections >= 1
            assert 0 < sent <= 5
        finally:
            prod.close()

    def test_writer_unacked_entry_cap(self):
        from m3_tpu.msg.producer import MessageWriter, _Message

        def connect():
            raise OSError("consumer down")

        w = MessageWriter(connect, retry_delay_s=5.0, max_unacked=4)
        for i in range(4):
            w.write(_Message(i, 0, b"v", refs=1))
        with pytest.raises(Backpressure):
            w.write(_Message(99, 0, b"v", refs=1))
        # re-write of an ALREADY-QUEUED id is not new growth: allowed
        w.write(_Message(2, 0, b"v", refs=1))
        assert w.unacked() == 4
        w.close()

    def test_unrouted_buffer_cap(self):
        from m3_tpu.msg.producer import ConsumerServiceWriter, _Message

        csw = ConsumerServiceWriter("svc", lambda: None,
                                    connect=lambda ep: None,
                                    max_unacked=3)
        for i in range(3):
            assert not csw.write(_Message(i, 0, b"v", refs=1))
        with pytest.raises(Backpressure):
            csw.write(_Message(9, 0, b"v", refs=1))
        assert csw.unacked() == 3

    def test_partial_fanout_unwound_on_backpressure(self):
        """Two consumer services, the second full: the message must not
        stay queued on the first (a half-delivered message retried
        forever on one service while the caller saw failure)."""
        from m3_tpu.msg import ConsumerService, Producer, Topic

        prod = Producer(Topic("t", 2, (ConsumerService("a"),
                                       ConsumerService("b"))),
                        {"a": lambda: None, "b": lambda: None},
                        retry_delay_s=5.0, max_unacked=2)
        try:
            prod.publish(0, b"m1")
            prod.publish(0, b"m2")
            with pytest.raises(Backpressure):
                prod.publish(0, b"m3")
            # m3 is tracked NOWHERE: both unrouted pens hold exactly m1,m2
            assert prod.unacked() == 4  # 2 messages x 2 services
            assert prod.buffered_bytes() == 4  # m1+m2 only
        finally:
            prod.close()


class TestConsumerInflightWatermark:
    def test_bounded_concurrent_handler_work(self):
        from m3_tpu.msg.consumer import Consumer
        from m3_tpu.rpc import wire

        active = [0]
        max_active = [0]
        done = [0]
        lock = threading.Lock()

        def handler(shard, value):
            with lock:
                active[0] += 1
                max_active[0] = max(max_active[0], active[0])
            time.sleep(0.05)
            with lock:
                active[0] -= 1
                done[0] += 1

        cons = Consumer(handler, max_inflight=1).start()
        socks = []
        try:
            host, port = cons.endpoint.rsplit(":", 1)
            for ci in range(3):
                s = socket.create_connection((host, int(port)), timeout=5)
                wire.write_frame(s, {"t": "msg", "shard": 0, "id": ci,
                                     "sent_at": 0, "value": b"v",
                                     "src": 1000 + ci})
                socks.append(s)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                with lock:
                    if done[0] == 3:
                        break
                time.sleep(0.01)
            with lock:
                assert done[0] == 3
                assert max_active[0] == 1, \
                    f"inflight watermark violated: {max_active[0]}"
        finally:
            for s in socks:
                s.close()
            cons.close()


# ------------------------------------------------------------------- loadgen


class TestLoadGen:
    def test_schedule_is_pure_function_of_seed(self):
        from m3_tpu.testing.loadgen import LoadSchedule, Phase

        kw = dict(base_rate=200,
                  phases=(Phase("base", 0.5, 1.0), Phase("spike", 0.5, 3.0)),
                  kinds=(("q", 3.0), ("w", 1.0)))
        a = LoadSchedule(seed=7, **kw)
        assert a.arrivals() == LoadSchedule(seed=7, **kw).arrivals()
        assert a.arrivals() != LoadSchedule(seed=8, **kw).arrivals()

    def test_phase_counts_exact_and_sorted(self):
        from m3_tpu.testing.loadgen import LoadSchedule, Phase

        sched = LoadSchedule(seed=3, base_rate=100,
                             phases=(Phase("base", 0.5, 1.0),
                                     Phase("spike", 0.5, 3.0)))
        arr = sched.arrivals()
        times = [t for t, _, _ in arr]
        assert times == sorted(times)
        assert sum(1 for _, _, ph in arr if ph == "base") == 50
        assert sum(1 for _, _, ph in arr if ph == "spike") == 150
        assert all(0 <= t < 1.0 for t in times)

    def test_open_loop_records_every_arrival(self):
        from m3_tpu.testing.loadgen import LoadGen, LoadSchedule, Phase

        sched = LoadSchedule(seed=5, base_rate=100,
                             phases=(Phase("p", 0.3, 1.0),),
                             kinds=(("ok", 3.0), ("boom", 1.0)))

        def fn(kind):
            if kind == "boom":
                raise ValueError("injected")

        report = LoadGen(sched).run(fn)
        assert len(report.records) == 30
        out = report.outcomes()
        assert out.get("ok", 0) + out.get("ValueError", 0) == 30
        assert out.get("ValueError", 0) > 0
        assert report.throughput("p") == pytest.approx(
            out.get("ok", 0) / 0.3)


# ------------------------------------------------- per-tenant fair share


class TestTenantFairShareWindow:
    """Per-tenant weighted fair-share over the sliding window
    (utils/limits.py, DAGOR-style): one noisy tenant saturates its OWN
    share of a kind's per-second budget, never the whole window."""

    def _limits(self, per_second=100.0, weights=None):
        t = [0.0]
        lims = QueryLimits(
            clock=lambda: t[0],
            docs_matched=LimitOptions(
                per_second=per_second, tenant_fair=True,
                tenant_weights=weights))
        return lims, t

    def test_noisy_tenant_capped_at_its_share(self):
        lims, _ = self._limits()
        # Lone tenant's share: 100 * 1/(0 active + 1 + 1 reserve) = 50.
        lims.charge("docs_matched", 50, tenant=b"noisy")
        with pytest.raises(ResourceExhausted, match="fair share"):
            lims.charge("docs_matched", 1, tenant=b"noisy")
        assert lims.tenant_usage("docs_matched", b"noisy") == 50

    def test_quiet_tenant_unaffected_by_noisy_burst(self):
        lims, _ = self._limits()
        lims.charge("docs_matched", 50, tenant=b"noisy")
        with pytest.raises(ResourceExhausted):
            lims.charge("docs_matched", 10, tenant=b"noisy")
        # The noisy tenant consumed only ITS share: a quiet tenant
        # arriving mid-burst still finds budget (share with one other
        # active tenant: 100 * 1/(1 + 1 + 1) = 33.3).
        lims.charge("docs_matched", 30, tenant=b"quiet")
        assert lims.tenant_usage("docs_matched", b"quiet") == 30

    def test_rejected_tenant_charge_leaves_nothing_charged(self):
        lims, _ = self._limits()
        lims.charge("docs_matched", 50, tenant=b"noisy")
        before = lims.tenant_usage("docs_matched", b"noisy")
        with pytest.raises(ResourceExhausted):
            lims.charge("docs_matched", 25, tenant=b"noisy")
        assert lims.tenant_usage("docs_matched", b"noisy") == before
        # the global window was not charged either: an untenanted charge
        # can still spend the remaining 50
        lims.charge("docs_matched", 50)

    def test_critical_bypasses_tenant_cap_never_the_window(self):
        lims, _ = self._limits()
        lims.charge("docs_matched", 50, tenant=b"noisy")
        # CRITICAL work from the saturated tenant is not tenant-shed...
        lims.charge("docs_matched", 40, tenant=b"noisy", critical=True)
        # ...but the docs-matched WINDOW itself still applies to it.
        with pytest.raises(ResourceExhausted):
            lims.charge("docs_matched", 20, tenant=b"noisy", critical=True)

    def test_weighted_tenants_split_proportionally(self):
        lims, _ = self._limits(weights=((b"big", 3.0),))
        # big alone: 100 * 3/(0 + 3 + 1) = 75; default-weight tenant
        # alongside: 100 * 1/(3 + 1 + 1) = 20.
        lims.charge("docs_matched", 75, tenant=b"big")
        with pytest.raises(ResourceExhausted):
            lims.charge("docs_matched", 1, tenant=b"big")
        lims.charge("docs_matched", 20, tenant=b"small")
        with pytest.raises(ResourceExhausted):
            lims.charge("docs_matched", 1, tenant=b"small")

    def test_idle_tenant_expires_and_share_recovers(self):
        lims, t = self._limits()
        lims.charge("docs_matched", 40, tenant=b"a")
        lims.charge("docs_matched", 30, tenant=b"b")
        with pytest.raises(ResourceExhausted):
            lims.charge("docs_matched", 30, tenant=b"b")  # share is 33.3
        t[0] += 1.1  # a's window usage fully expires
        # with a idle, b is alone again: share back to 50
        lims.charge("docs_matched", 20, tenant=b"b")
        assert lims.tenant_usage("docs_matched", b"a") == 0

    def test_untenanted_charges_see_only_the_global_window(self):
        lims, _ = self._limits()
        lims.charge("docs_matched", 90)
        with pytest.raises(ResourceExhausted):
            lims.charge("docs_matched", 20)

    def test_scope_carries_tenant(self):
        lims, _ = self._limits()
        with lims.scope("q", tenant=b"noisy") as sc:
            sc.charge("docs_matched", 50)
            with pytest.raises(ResourceExhausted, match="fair share"):
                sc.charge("docs_matched", 10)

    def test_tenant_of_extraction(self):
        from m3_tpu.utils.limits import tenant_of

        assert tenant_of(b"acme.requests.count;host=x") == b"acme"
        assert tenant_of(b"acme.requests") == b"acme"
        # an id without a dot is its own tenant (single-tenant degrade)
        assert tenant_of(b"requests;host=x") == b"requests"
        assert tenant_of(b"bare") == b"bare"


class TestTenantFairShareGate:
    """Per-tenant fair-share on the ingest AdmissionGate
    (utils/health.py): engaged only past the high watermark, CRITICAL
    never tenant-shed."""

    def _gate(self, capacity=8, high=0.5, weights=None):
        return AdmissionGate(capacity, high_watermark=high,
                             tracker=HealthTracker(),
                             tenant_weights=weights)

    def test_noisy_tenant_sheds_at_its_share(self):
        g = self._gate()  # capacity 8, high watermark 4
        # below the watermark the tenant cap is not engaged
        assert g.try_admit(4, Priority.NORMAL, tenant=b"noisy")
        # past it, a lone tenant's share is 8 * 1/(0 + 1 + 1) = 4
        assert not g.try_admit(1, Priority.NORMAL, tenant=b"noisy")
        assert g.shed_tenant == 1
        assert g.stats()["tenants"] == {b"noisy": 4}

    def test_quiet_tenant_still_admitted_past_watermark(self):
        g = self._gate()
        g.admit(4, Priority.NORMAL, tenant=b"noisy")
        # quiet tenant mid-burst: share 8 * 1/(1 + 1 + 1) = 2.67
        assert g.try_admit(2, Priority.NORMAL, tenant=b"quiet")
        assert not g.try_admit(1, Priority.NORMAL, tenant=b"quiet")
        assert g.depth() == 6

    def test_critical_never_tenant_shed(self):
        g = self._gate()
        g.admit(4, Priority.NORMAL, tenant=b"noisy")
        assert not g.try_admit(1, Priority.NORMAL, tenant=b"noisy")
        assert g.try_admit(1, Priority.CRITICAL, tenant=b"noisy")
        assert g.shed["critical"] == 0

    def test_release_clears_tenant_depth(self):
        g = self._gate()
        g.admit(4, Priority.NORMAL, tenant=b"noisy")
        g.release(4, tenant=b"noisy")
        assert g.stats()["tenants"] == {}
        assert g.try_admit(4, Priority.NORMAL, tenant=b"noisy")

    def test_weighted_tenant_gets_bigger_share(self):
        g = self._gate(weights={b"big": 3.0})
        # big alone past the watermark: share 8 * 3/(0 + 3 + 1) = 6
        assert g.try_admit(4, Priority.NORMAL, tenant=b"big")
        assert g.try_admit(2, Priority.NORMAL, tenant=b"big")
        assert not g.try_admit(1, Priority.NORMAL, tenant=b"big")

    def test_untenanted_admits_unchanged_by_fairness(self):
        g = self._gate()
        g.admit(4, Priority.NORMAL, tenant=b"noisy")
        # untenanted NORMAL work is still bounded only by capacity
        assert g.try_admit(4, Priority.NORMAL)
        assert not g.try_admit(1, Priority.NORMAL)

    def test_backpressure_message_names_tenant(self):
        g = self._gate()
        g.admit(4, Priority.NORMAL, tenant=b"noisy")
        with pytest.raises(Backpressure, match="tenant b'noisy'"):
            g.admit(1, Priority.NORMAL, tenant=b"noisy")
