"""Crash-safe columnar recovery (reference test model: the commitlog
reader/iterator tests, dbnode/digest validation, and the dtest
kill-restart destructive scenarios).

Tier-1 promotion of scripts/fuzz_durability.py's invariants — seeded
SUBSETS run here on every pass, the open-ended campaign stays in the
script — plus the columnar-recovery bit-identity contracts (batched
replay and bootstrap vs the retained `_ref` per-entry oracles) and the
kill -9 disaster drill (KillRestartScenario: a REAL dbnode child under
seeded open-loop load, SIGKILLed, restarted, zero acked-write loss)."""

import os
import shutil
import tempfile
import zlib

import numpy as np
import pytest

from m3_tpu.parallel.sharding import ShardSet
from m3_tpu.persist import commitlog as cl
from m3_tpu.persist import fs as pfs
from m3_tpu.persist.diskio import CorruptionError
from m3_tpu.persist.fs import FilesetReader, PersistManager, fileset_complete
from m3_tpu.storage import bootstrap as bs
from m3_tpu.storage.block import encode_block
from m3_tpu.storage.database import Database
from m3_tpu.storage.mediator import Mediator
from m3_tpu.storage.namespace import NamespaceOptions
from m3_tpu.storage.series import SeriesRegistry
from m3_tpu.testing.scenario import (KillRestartOptions, KillRestartScenario)
from m3_tpu.utils import xtime
from m3_tpu.utils.checksum import adler32_rows
from m3_tpu.utils.instrument import ROOT

NS = b"default"
BLOCK = 2 * xtime.HOUR
T0 = 1_600_000_000 * xtime.SECOND - (1_600_000_000 * xtime.SECOND) % BLOCK


# ---------------------------------------------------------------------------
# vectorized adler32
# ---------------------------------------------------------------------------


class TestAdler32Rows:
    def test_bit_identical_to_zlib(self, rng):
        for s, n, dtype in [(1, 1, np.uint8), (7, 33, np.uint8),
                            (5, 16, np.uint32), (3, 0, np.uint8),
                            (12, 129, np.uint32), (4, 7, np.int64)]:
            if dtype == np.uint8:
                mat = rng.integers(0, 256, (s, max(n, 1)),
                                   dtype=np.uint8)[:, :n]
            else:
                mat = rng.integers(0, 2**31 - 1, (s, n)).astype(dtype)
            got = adler32_rows(mat)
            want = [zlib.adler32(np.ascontiguousarray(mat)[i].tobytes())
                    for i in range(s)]
            assert got.tolist() == want

    def test_non_contiguous_rows(self, rng):
        mat = rng.integers(0, 256, (6, 40), dtype=np.uint8)[::2, 1::3]
        got = adler32_rows(mat)
        want = [zlib.adler32(np.ascontiguousarray(mat)[i].tobytes())
                for i in range(mat.shape[0])]
        assert got.tolist() == want

    def test_rejects_non_matrix(self):
        with pytest.raises(ValueError):
            adler32_rows(np.zeros(8, np.uint8))


# ---------------------------------------------------------------------------
# commitlog: torn tails, corruption isolation, batched-vs-ref identity
# ---------------------------------------------------------------------------


def _write_log(tmp, rng, n_entries=120, rotate_p=0.12):
    """Unique-entry stream across rotated files -> (dir, per_file)."""
    d = str(tmp)
    log = cl.CommitLog(d, strategy=cl.Strategy.WRITE_WAIT)
    per_file = [[]]
    for seq in range(n_entries):
        entry = (b"ns%d" % rng.integers(3), b"s%d" % rng.integers(8),
                 int(seq), float(seq))
        log.write(*entry[:2], entry[2], entry[3])
        per_file[-1].append(entry)
        if rng.random() < rotate_p:
            log.rotate()
            per_file.append([])
    log.close()
    return d, per_file


def _run_iter(gen):
    """(entries, exception-name-or-None): corrupt streams must fail the
    SAME way in the batched decoder as in the per-entry oracle."""
    out = []
    try:
        for e in gen:
            out.append(e)
        return out, None
    except Exception as e:  # noqa: BLE001 — equality of failure is the point
        return out, type(e).__name__


def _corrupt(path, rng):
    data = bytearray(open(path, "rb").read())
    kind = ["truncate", "flip", "insert", "delete"][int(rng.integers(4))]
    if not data:
        kind = "insert"
    if kind == "truncate":
        data = data[: int(rng.integers(0, len(data)))]
    elif kind == "flip":
        for _ in range(int(rng.integers(1, 5))):
            i = int(rng.integers(0, len(data)))
            data[i] ^= int(rng.integers(1, 256))
    elif kind == "insert":
        i = int(rng.integers(0, len(data) + 1))
        junk = bytes(rng.integers(0, 256, int(rng.integers(1, 17)),
                                  dtype=np.uint8))
        data = bytes(data[:i]) + junk + bytes(data[i:])
    else:
        i = int(rng.integers(0, len(data)))
        j = int(rng.integers(i + 1, min(len(data), i + 64) + 1))
        data = data[:i] + data[j:]
    with open(path, "wb") as f:
        f.write(bytes(data))
    return kind


class TestCommitlogReplay:
    def test_torn_tail_chunk_dropped(self, tmp_path, rng):
        d, per_file = _write_log(tmp_path, rng, rotate_p=0.0)
        want = per_file[0]
        fname = sorted(os.listdir(d))[-1]
        # A half-written chunk: header promises 512 bytes, 24 arrive.
        with open(os.path.join(d, fname), "ab") as f:
            f.write(cl._CHUNK_HEADER.pack(512, 0xBAD) + b"x" * 24)
        assert list(cl.replay(d)) == want
        assert list(cl.replay_ref(d)) == want
        flat = [(ns, sid, int(t), float(v))
                for b in cl.replay_batches(d)
                for ns, sid, t, v in zip(b.namespaces, b.ids, b.t_ns,
                                         b.values)]
        assert flat == want

    def test_mid_file_truncation_keeps_prefix(self, tmp_path, rng):
        d, per_file = _write_log(tmp_path, rng, n_entries=40, rotate_p=0.0)
        fname = sorted(os.listdir(d))[-1]
        path = os.path.join(d, fname)
        size = os.path.getsize(path)
        with open(path, "rb+") as f:
            f.truncate(size - 11)  # tear inside the final chunk
        got = list(cl.replay(d))
        assert got == per_file[0][: len(got)]  # an exact PREFIX, nothing made up
        assert len(got) < len(per_file[0])

    @pytest.mark.parametrize("seed", [1, 2, 3, 4, 5, 6])
    def test_corruption_batched_vs_ref_bit_identity(self, tmp_path, seed):
        """Seeded fuzz subset: one corrupted file per round — the
        batched decoder must yield the SAME entries AND fail the same
        way as the per-entry oracle, and damage must stay inside the
        corrupted file."""
        rng = np.random.default_rng(seed)
        d, per_file = _write_log(tmp_path, rng)
        files = sorted(f for f in os.listdir(d) if f.startswith("commitlog-"))
        assert len(files) == len(per_file)
        k = int(rng.integers(len(files)))
        _corrupt(os.path.join(d, files[k]), rng)
        ref, ref_err = _run_iter(cl.replay_ref(d))
        new, new_err = _run_iter(cl.replay(d))
        assert (new, new_err) == (ref, ref_err)
        # Cross-file isolation: files before/after the damaged one
        # replay exactly (ref semantics proven by the fuzz campaign;
        # here we assert the batched path preserves them).
        flat_expect = [e for i, f in enumerate(per_file) if i != k for e in f]
        surviving = [e for e in new if e not in per_file[k]]
        assert surviving == [e for e in flat_expect if e in surviving]
        pre = [e for i, f in enumerate(per_file) if i < k for e in f]
        assert new[: len(pre)] == pre or ref_err is not None

    def test_str_tags_never_abort_the_append(self, tmp_path):
        """The JSON ingest surfaces hand over str-keyed tag dicts; the
        WAL append must normalize them (or degrade to untagged), never
        raise — the shard buffer was already written, so an abort here
        silently diverges served data from the WAL."""
        d = str(tmp_path)
        log = cl.CommitLog(d, strategy=cl.Strategy.WRITE_WAIT)
        log.write(b"ns", b"s1", 1, 1.0, tags={"host": "a"})     # str/str
        log.write(b"ns", b"s2", 2, 2.0, tags={b"k": object()})  # hopeless
        log.close()
        batches = list(cl.replay_batches(d))
        entries = [(sid, t.item()) for b in batches
                   for sid, t in zip(b.ids, b.t_ns)]
        assert entries == [(b"s1", 1), (b"s2", 2)]
        tags = {sid: tg for b in batches
                for sid, tg in zip(b.ids, b.tags)}
        assert tags[b"s1"] == {b"host": b"a"}  # normalized to bytes
        assert tags[b"s2"] is None             # degraded, not dropped

    def test_tagged_write_after_untagged_first_sighting(self, tmp_path):
        """A series whose FIRST write in a file is untagged must still
        get its tags into the WAL when a later tagged write arrives
        (a fresh tagged meta is emitted), or recovery cannot rebuild
        its index document."""
        d = str(tmp_path)
        log = cl.CommitLog(d, strategy=cl.Strategy.WRITE_WAIT)
        log.write(b"ns", b"s1", 1, 1.0)
        log.write(b"ns", b"s1", 2, 2.0, tags={b"k": b"v"})
        log.write(b"ns", b"s1", 3, 3.0)  # cached tagged ref reused
        log.close()
        assert list(cl.replay(d)) == [(b"ns", b"s1", 1, 1.0),
                                      (b"ns", b"s1", 2, 2.0),
                                      (b"ns", b"s1", 3, 3.0)]
        per_entry = [tg for b in cl.replay_batches(d) for tg in b.tags]
        assert per_entry[0] is None
        assert per_entry[1] == {b"k": b"v"}
        assert per_entry[2] == {b"k": b"v"}

    def test_unrecognized_format_file_skipped_not_misparsed(self, tmp_path,
                                                            rng):
        """A commitlog file without this format's header (older layout,
        foreign bytes) is SKIPPED with a warning — misparsing would
        fabricate (ns, id) pairs into shard buffers."""
        d, per_file = _write_log(tmp_path, rng, n_entries=20, rotate_p=0.0)
        # A v1-era file: chunked entries but no file header.
        legacy = os.path.join(d, "commitlog-00000099.bin")
        body = cl._DATA_ENTRY.pack(1, 0, 5, 5.0)
        with open(legacy, "wb") as f:
            f.write(cl._CHUNK_HEADER.pack(len(body), zlib.adler32(body)))
            f.write(body)
        assert list(cl.replay(d)) == per_file[0]
        assert list(cl.replay_ref(d)) == per_file[0]

    def test_streaming_positions_and_wrapper_types(self, tmp_path, rng):
        d, per_file = _write_log(tmp_path, rng, n_entries=30, rotate_p=0.3)
        batches = list(cl.replay_batches(d))
        # chunk positions are per-file monotonic and chunk-aligned
        by_file = {}
        for b in batches:
            assert b.end_offset > by_file.get(b.file_num, 0)
            by_file[b.file_num] = b.end_offset
        for b in batches:
            assert b.before((b.file_num, b.end_offset))
            assert not b.before((b.file_num, b.end_offset - 1))
            assert b.before((b.file_num + 1, 0))
        for ns, sid, t, v in cl.replay(d):
            assert type(t) is int and type(v) is float
            break


# ---------------------------------------------------------------------------
# fileset verification
# ---------------------------------------------------------------------------


def _mk_fileset(root, rng, n=12, w=9):
    reg = SeriesRegistry()
    ids = [b"fz.%d" % i for i in range(n)]
    for sid in ids:
        reg.get_or_create(sid)
    ts = (T0 + np.arange(w, dtype=np.int64)[None, :] * 10 * xtime.SECOND
          + np.zeros((n, 1), np.int64))
    vals = rng.integers(0, 50, size=(n, w)).astype(np.float64)
    blk = encode_block(T0, np.arange(n, dtype=np.int32), ts, vals,
                       np.full(n, w, np.int32))
    pm = PersistManager(root)
    return pm.write_block(NS, 1, blk, reg)


class TestFilesetVerification:
    @pytest.mark.parametrize("seed", [11, 12, 13, 14])
    def test_one_byte_corruption_detected(self, tmp_path, seed):
        """Seeded fuzz subset: one flipped byte in one component file
        must be DETECTED — incomplete fileset, raising verified reader,
        or raising row verification. A clean read of corrupt bytes is
        the failure this exists to catch."""
        rng = np.random.default_rng(seed)
        path = _mk_fileset(str(tmp_path), rng)
        assert fileset_complete(path)
        names = sorted(os.listdir(path))
        fname = names[int(rng.integers(len(names)))]
        fpath = os.path.join(path, fname)
        data = bytearray(open(fpath, "rb").read())
        if not data:
            pytest.skip("empty component")
        i = int(rng.integers(0, len(data)))
        data[i] ^= int(rng.integers(1, 256))
        with open(fpath, "wb") as f:
            f.write(bytes(data))
        if not fileset_complete(path):
            return  # checkpoint/digest chain flagged it
        with pytest.raises((ValueError, KeyError, OSError, IndexError)):
            reader = FilesetReader(path, verify=True)
            reader.verify_rows()
            reader.to_block()

    def test_row_checksums_vectorized_match_entries(self, tmp_path, rng):
        path = _mk_fileset(str(tmp_path), rng)
        reader = FilesetReader(path)
        reader.verify_rows()  # must pass clean
        sums = reader.row_checksums()
        by_row = {e.row: e.checksum for e in reader.entries}
        assert all(int(sums[r]) == c for r, c in by_row.items())

    def test_row_mismatch_detected_past_digests(self, tmp_path, rng):
        """Cross-wire the index against the data (digests recomputed so
        the file-level chain passes): only row verification catches it."""
        import json

        path = _mk_fileset(str(tmp_path), rng)
        reader = FilesetReader(path)
        e0 = reader.entries[0]
        idx_path = os.path.join(path, "index.bin")
        data = bytearray(open(idx_path, "rb").read())
        # flip a checksum byte of the first entry (offset 16..19 of the
        # fixed header) then recompute the digest chain around it
        data[16] ^= 0xFF
        with open(idx_path, "wb") as f:
            f.write(bytes(data))
        from m3_tpu.persist.fs import _adler
        digests = json.load(open(os.path.join(path, "digest.json")))
        digests["index.bin"] = _adler(idx_path)
        with open(os.path.join(path, "digest.json"), "w") as f:
            json.dump(digests, f)
        with open(os.path.join(path, "checkpoint.json"), "w") as f:
            json.dump({"digest": _adler(os.path.join(path, "digest.json"))},
                      f)
        assert fileset_complete(path)
        reader2 = FilesetReader(path, verify=True)  # digests all pass
        with pytest.raises(IOError, match="row checksum mismatch"):
            reader2.verify_rows()
        assert reader2.entries[0].id == e0.id

    def test_tmp_fileset_residue_ignored_and_cleaned(self, tmp_path, rng):
        """A SIGKILL between the checkpoint write and os.replace leaves
        a complete-looking '<kind>-<bs>.tmp' dir: listings must skip it
        (a crash must never wedge the next restart on int('...tmp')),
        and the mediator's cleanup removes it."""
        root = str(tmp_path)
        path = _mk_fileset(root, rng)  # ns shard-00001 fileset
        shard_dir = os.path.dirname(path)
        shutil.copytree(path, path + ".tmp")  # full chain inside .tmp
        pm = PersistManager(root)
        listed = pm.list_filesets(NS, 1)
        assert [p for _bs, p in listed] == [path]
        assert pm.list_snapshots(NS, 1) == []
        # cleanup sweeps the residue
        db = Database(ShardSet(2), clock=lambda: T0)
        db.create_namespace(NS, NamespaceOptions(index_enabled=False))
        Mediator(db, pm).cleanup(T0)
        assert not os.path.exists(path + ".tmp")
        assert os.path.exists(path)
        assert [p for _bs, p in pm.list_filesets(NS, 1)] == [path]

    def test_bloom_divergence_detected(self, tmp_path, rng):
        import json

        path = _mk_fileset(str(tmp_path), rng)
        bloom_path = os.path.join(path, "bloom.bin")
        data = bytearray(open(bloom_path, "rb").read())
        data[0] ^= 0x01
        with open(bloom_path, "wb") as f:
            f.write(bytes(data))
        from m3_tpu.persist.fs import _adler
        digests = json.load(open(os.path.join(path, "digest.json")))
        digests["bloom.bin"] = _adler(bloom_path)
        with open(os.path.join(path, "digest.json"), "w") as f:
            json.dump(digests, f)
        with open(os.path.join(path, "checkpoint.json"), "w") as f:
            json.dump({"digest": _adler(os.path.join(path, "digest.json"))},
                      f)
        with pytest.raises(IOError, match="bloom"):
            FilesetReader(path).verify_rows()


# ---------------------------------------------------------------------------
# region-targeted bit-flip corpus over the LAZY serve path
# ---------------------------------------------------------------------------


class TestRegionBitflipCorpus:
    """Seeded subset of the fuzzer's region corpus
    (scripts/fuzz_durability.py region_round): one flipped byte in one
    NAMED fileset region, read back through the lazy serve path
    (verify=False reader -> SealedBlock row verification, and the
    Seeker point-lookup route). The invariant is detect-or-serve-
    correct: every read either raises typed or returns bit-identical
    data — a clean read of WRONG bytes is the only failure."""

    REGIONS = {
        "index": pfs.INDEX_FILE, "data": pfs.DATA_FILE,
        "bloom": pfs.BLOOM_FILE, "checkpoint": pfs.CHECKPOINT_FILE,
    }
    _TYPED = (CorruptionError, ValueError, KeyError, OSError, IndexError)

    @pytest.mark.parametrize("region", sorted(REGIONS))
    @pytest.mark.parametrize("seed", [21, 22, 23])
    def test_detect_or_serve_correct(self, tmp_path, region, seed):
        rng = np.random.default_rng(seed)
        path = _mk_fileset(str(tmp_path), rng)
        clean_blk, clean_ids = FilesetReader(path, verify=True).to_block()
        truth_ts, truth_vs, truth_np = clean_blk.read_all()
        sk0 = pfs.Seeker(path)
        truth_rows = {sid: sk0.seek(sid) for sid in clean_ids}
        fpath = os.path.join(path, self.REGIONS[region])
        data = bytearray(open(fpath, "rb").read())
        assert data, f"{region} region unexpectedly empty"
        i = int(rng.integers(0, len(data)))
        data[i] ^= int(rng.integers(1, 256))
        with open(fpath, "wb") as f:
            f.write(bytes(data))
        if not fileset_complete(path):
            return  # detected: checkpoint chain flagged it
        # Serve path 1: lazy block materialization + row verify.
        try:
            blk, ids = FilesetReader(path, verify=False).to_block()
            ts, vs, npts = blk.read_all()
        except self._TYPED:
            pass  # detected, typed
        else:
            assert list(ids) == list(clean_ids)
            assert np.array_equal(truth_ts, ts)
            assert np.array_equal(truth_vs, vs, equal_nan=True)
            assert np.array_equal(truth_np, npts)
        # Serve path 2: Seeker point lookups (bloom + index + row adler
        # route — distinct bytes from to_block's matrix route). seek
        # returns the packed (words row, nbits, npoints) triple.
        try:
            sk = pfs.Seeker(path)
            for sid in clean_ids:
                got = sk.seek(sid)
                assert got is not None, \
                    f"{region} flip at {i} dropped {sid!r} from seek"
                want = truth_rows[sid]
                assert np.array_equal(want[0], got[0])
                assert want[1:] == got[1:]
        except self._TYPED:
            pass  # detected, typed

    def test_clean_fileset_serves_both_routes(self, tmp_path, rng):
        """The corpus's negative: no flip -> both serve routes return
        the written data (guards against detection-by-default)."""
        path = _mk_fileset(str(tmp_path), rng)
        blk, ids = FilesetReader(path, verify=False).to_block()
        _ts, _vs, npts = blk.read_all()  # row verification passes
        sk = pfs.Seeker(path)
        for r, sid in enumerate(ids):
            got = sk.seek(sid)
            assert got is not None
            assert got[2] == int(npts[r])


# ---------------------------------------------------------------------------
# bootstrap: batched recovery vs retained per-entry oracles
# ---------------------------------------------------------------------------


def _seed_recovery_dir(root, rng, n_series=60, num_shards=4):
    """Kill -9 shaped dir: flushed old block + snapshotted warm block +
    WAL tail past the snapshot (incl. an overwrite of a snapshotted
    point)."""
    now = {"t": T0 + xtime.MINUTE}
    log = cl.CommitLog(os.path.join(root, "cl"))
    db = Database(ShardSet(num_shards), commitlog=log, clock=lambda: now["t"])
    db.create_namespace(NS, NamespaceOptions(index_enabled=False))
    pm = PersistManager(os.path.join(root, "data"))
    ids = [b"rec-%04d" % i for i in range(n_series)]
    db.write_batch(NS, ids, np.full(n_series, T0, np.int64),
                   rng.standard_normal(n_series))
    now["t"] = T0 + BLOCK + 11 * xtime.MINUTE
    db.tick()
    db.flush(pm)
    b1 = T0 + BLOCK
    for w in range(3):
        tsw = b1 + (12 + w) * xtime.MINUTE
        now["t"] = tsw
        db.write_batch(NS, ids, np.full(n_series, tsw, np.int64),
                       rng.standard_normal(n_series))
        log.flush()
    Mediator(db, pm).snapshot(now["t"])
    tsw = b1 + 20 * xtime.MINUTE
    now["t"] = tsw
    db.write_batch(NS, ids[: n_series // 2],
                   np.full(n_series // 2, tsw, np.int64),
                   rng.standard_normal(n_series // 2))
    db.write_batch(NS, ids[:5], np.full(5, b1 + 12 * xtime.MINUTE, np.int64),
                   np.full(5, 424242.0))  # overwrite a snapshotted point
    log.flush()
    # Abandoned WITHOUT close(): on-disk state == SIGKILL.
    return db, pm, ids, now


def _recover(root, pm, now, num_shards, path):
    """path='new' -> batched tiles + columnar WAL; 'ref' -> retained
    per-entry oracles; 'chain' -> the real BootstrapProcess."""
    db2 = Database(ShardSet(num_shards), clock=lambda: now["t"])
    db2.create_namespace(NS, NamespaceOptions(index_enabled=False))
    ns = db2.namespace(NS)
    ctx = bs.BootstrapContext(persist=pm, commitlog_dir=os.path.join(root, "cl"),
                              shard_lookup=db2.shard_set.lookup)
    proc = bs.BootstrapProcess(
        chain=("filesystem", "commitlog", "uninitialized_topology"), ctx=ctx)
    if path == "chain":
        proc.run(db2, now_ns=now["t"])
        return db2
    req = proc.target_ranges(ns, now["t"])
    claimed = proc.bootstrappers[0].bootstrap(ns, req, ctx)
    rem = req.subtract(claimed)
    if path == "new":
        positions = bs.load_snapshots(ns, rem, ctx)
        assert bs.replay_wal(ns, rem, ctx, positions)
    else:
        bs.load_snapshots_ref(ns, rem, ctx)
        assert bs.replay_wal_ref(ns, rem, ctx)
    db2.mark_bootstrapped()
    return db2


class TestBootstrapOracle:
    @pytest.mark.parametrize("seed", [21, 22, 23])
    def test_recovery_read_identical_to_ref_and_origin(self, tmp_path, seed):
        rng = np.random.default_rng(seed)
        root = str(tmp_path)
        db, pm, ids, now = _seed_recovery_dir(root, rng)
        dn = _recover(root, pm, now, 4, "new")
        dr = _recover(root, pm, now, 4, "ref")
        dc = _recover(root, pm, now, 4, "chain")
        end = now["t"] + xtime.HOUR
        for sid in ids:
            tn, vn = dn.read(NS, sid, 0, end)
            for other in (dr, dc, db):
                t2, v2 = other.read(NS, sid, 0, end)
                np.testing.assert_array_equal(tn, t2)
                np.testing.assert_array_equal(vn, v2)
        for s in range(4):
            assert (dn.namespace(NS).shards[s].registry.all_ids()
                    == dr.namespace(NS).shards[s].registry.all_ids())
        # Seal both (the recovered-buffer drain rides merge_same_start
        # over the snapshot tile on the new path) and re-compare.
        now["t"] = T0 + 2 * BLOCK + 21 * xtime.MINUTE
        dn.tick()
        dr.tick()
        for sid in ids:
            tn, vn = dn.read(NS, sid, 0, end)
            tr2, vr2 = dr.read(NS, sid, 0, end)
            np.testing.assert_array_equal(tn, tr2)
            np.testing.assert_array_equal(vn, vr2)

    def test_wal_only_buffer_bit_identity(self, tmp_path, rng):
        """Pure-WAL recovery (no snapshots/filesets): the batched path
        must leave buffer COLUMNS and registries bit-identical to the
        per-entry oracle — same entries, same order, same dtypes."""
        root = str(tmp_path)
        now = {"t": T0 + xtime.MINUTE}
        log = cl.CommitLog(os.path.join(root, "cl"))
        db = Database(ShardSet(4), commitlog=log, clock=lambda: now["t"])
        db.create_namespace(NS, NamespaceOptions(index_enabled=False))
        ids = [b"wal-%03d" % i for i in range(40)]
        for w in range(4):
            tsw = T0 + w * xtime.MINUTE
            now["t"] = tsw + xtime.MINUTE
            db.write_batch(NS, ids, np.full(len(ids), tsw, np.int64),
                           rng.standard_normal(len(ids)))
            log.flush()
        dbs = {}
        for path in ("new", "ref"):
            db2 = Database(ShardSet(4), clock=lambda: now["t"])
            db2.create_namespace(NS, NamespaceOptions(index_enabled=False))
            ns = db2.namespace(NS)
            ctx = bs.BootstrapContext(commitlog_dir=os.path.join(root, "cl"),
                                      shard_lookup=db2.shard_set.lookup)
            req = bs.BootstrapProcess(ctx=ctx).target_ranges(ns, now["t"])
            fn = bs.replay_wal if path == "new" else bs.replay_wal_ref
            assert fn(ns, req, ctx) is True
            dbs[path] = db2
        for s in range(4):
            shn = dbs["new"].namespace(NS).shards[s]
            shr = dbs["ref"].namespace(NS).shards[s]
            assert shn.registry.all_ids() == shr.registry.all_ids()
            assert sorted(shn.buffer.buckets) == sorted(shr.buffer.buckets)
            for bstart, bucket in shn.buffer.buckets.items():
                a, b = bucket.cols.view(), shr.buffer.buckets[bstart].cols.view()
                for x, y in zip(a, b):
                    np.testing.assert_array_equal(x, y)
                    assert x.dtype == y.dtype

    def test_recovery_rebuilds_reverse_index_from_wal_tags(self, tmp_path):
        """Tagged series must be QUERYABLE after recovery, not merely
        readable by id: the WAL meta entries carry encoded tags (the
        reference commitlog's EncodedTags) and replay re-indexes series
        whose index blocks were never flushed — including series whose
        DATA the snapshot position-skip drops. The recovered node must
        answer the same index query with the same ids, and serve the
        same PromQL range, as the pre-kill node."""
        from m3_tpu.index.query import TermQuery

        root = str(tmp_path)
        now = {"t": T0 + 2 * xtime.HOUR}
        log = cl.CommitLog(os.path.join(root, "cl"),
                           strategy=cl.Strategy.WRITE_WAIT)
        db = Database(ShardSet(4), commitlog=log, clock=lambda: now["t"])
        db.ensure_namespace(NS, NamespaceOptions())  # index ON
        pm = PersistManager(os.path.join(root, "data"))
        med = Mediator(db, pm)
        base = now["t"]
        for i in range(1, 9):
            sid = b"idx_cpu;host=h%d" % (i % 3)
            db.write(NS, sid, base - 60 * xtime.SECOND + i * xtime.SECOND,
                     100.0 + i,
                     tags={b"__name__": b"idx_cpu", b"host": b"h%d" % (i % 3)})
            # Mediator cadence between writes: snapshots cover the lot,
            # so WAL data chunks are position-skipped on recovery — the
            # index docs must STILL come back.
            med.run_once(now["t"])
        # Abandoned WITHOUT close(): on-disk state == SIGKILL.
        db2 = Database(ShardSet(4), clock=lambda: now["t"])
        db2.ensure_namespace(NS, NamespaceOptions())
        proc = bs.BootstrapProcess(
            chain=("filesystem", "commitlog", "uninitialized_topology"),
            ctx=bs.BootstrapContext(
                persist=pm, commitlog_dir=os.path.join(root, "cl"),
                shard_lookup=db2.shard_set.lookup))
        proc.run(db2, now_ns=now["t"])
        q = TermQuery(b"__name__", b"idx_cpu")
        want_ids = sorted(db.query_ids(NS, q))
        got_ids = sorted(db2.query_ids(NS, q))
        assert want_ids == got_ids and len(got_ids) == 3
        for sid in got_ids:
            t1, v1 = db.read(NS, sid, 0, base + 1)
            t2, v2 = db2.read(NS, sid, 0, base + 1)
            np.testing.assert_array_equal(t1, t2)
            np.testing.assert_array_equal(v1, v2)
        # registry tags recovered too (CompleteTags / aggregate paths)
        for sid in got_ids:
            shard = db2.namespace(NS).shards[db2.shard_set.lookup(sid)]
            tags = shard.registry.tags_of(shard.registry.get(sid))
            assert tags is not None and tags[b"__name__"] == b"idx_cpu"

    def test_warm_snapshot_tile_not_flushed_before_seal(self, tmp_path, rng):
        """A snapshot tile recovered for a STILL-WRITABLE window must
        not flush: a tile-only fileset would make the NEXT restart's
        filesystem bootstrapper claim the whole block range and
        range-filter the WAL tail out of replay — acked writes lost on
        the second kill. The tile flushes only once the window is cold
        (post-seal, merged with the replayed tail)."""
        root = str(tmp_path)
        now = {"t": T0 + 30 * xtime.MINUTE}
        log = cl.CommitLog(os.path.join(root, "cl"),
                           strategy=cl.Strategy.WRITE_WAIT)
        db = Database(ShardSet(2), commitlog=log, clock=lambda: now["t"])
        db.create_namespace(NS, NamespaceOptions(index_enabled=False))
        pm = PersistManager(os.path.join(root, "data"))
        ids = [b"warm-%02d" % i for i in range(12)]
        db.write_batch(NS, ids, np.full(len(ids), now["t"], np.int64),
                       rng.standard_normal(len(ids)))
        Mediator(db, pm).snapshot(now["t"])
        post_t = now["t"] + xtime.MINUTE
        now["t"] = post_t
        db.write_batch(NS, ids[:6], np.full(6, post_t, np.int64),
                       rng.standard_normal(6))  # WAL tail past the snapshot
        # kill #1: restart while the block is STILL warm
        db2 = Database(ShardSet(2), clock=lambda: now["t"])
        db2.create_namespace(NS, NamespaceOptions(index_enabled=False))
        proc = bs.BootstrapProcess(
            chain=("filesystem", "commitlog", "uninitialized_topology"),
            ctx=bs.BootstrapContext(
                persist=pm, commitlog_dir=os.path.join(root, "cl"),
                shard_lookup=db2.shard_set.lookup))
        proc.run(db2, now_ns=now["t"])
        med2 = Mediator(db2, pm)
        med2.run_once(now["t"])  # tick + flush + snapshot + cleanup, warm
        for sh in (0, 1):
            assert pm.list_filesets(NS, sh) == [], \
                "warm snapshot tile flushed before seal"
        # kill #2, still warm: recovery must serve EVERYTHING
        db3 = Database(ShardSet(2), clock=lambda: now["t"])
        db3.create_namespace(NS, NamespaceOptions(index_enabled=False))
        bs.BootstrapProcess(
            chain=("filesystem", "commitlog", "uninitialized_topology"),
            ctx=bs.BootstrapContext(
                persist=pm, commitlog_dir=os.path.join(root, "cl"),
                shard_lookup=db3.shard_set.lookup)).run(db3, now_ns=now["t"])
        for sid in ids:
            t1, v1 = db.read(NS, sid, 0, now["t"] + xtime.HOUR)
            t3, v3 = db3.read(NS, sid, 0, now["t"] + xtime.HOUR)
            np.testing.assert_array_equal(t1, t3)
            np.testing.assert_array_equal(v1, v3)
        # ... and once the window is COLD, the merged block flushes.
        now["t"] = T0 + BLOCK + 11 * xtime.MINUTE
        med2.run_once(now["t"])
        assert any(pm.list_filesets(NS, sh) for sh in (0, 1))

    def test_same_chunk_untagged_then_tagged_series_indexed(self, tmp_path):
        """A series created untagged whose tagged entry lands in the
        SAME WAL chunk (one write_batch) must still get its reverse-
        index document on recovery — the hook reads the registry's
        backfilled tags, not the first-occurrence position."""
        from m3_tpu.index.query import TermQuery

        root = str(tmp_path)
        now = {"t": T0 + 30 * xtime.MINUTE}
        log = cl.CommitLog(os.path.join(root, "cl"))
        db = Database(ShardSet(2), commitlog=log, clock=lambda: now["t"])
        db.ensure_namespace(NS, NamespaceOptions())  # index ON
        tg = {b"__name__": b"mix", b"host": b"a"}
        db.write_batch(NS, [b"mix;host=a", b"mix;host=a"],
                       np.full(2, now["t"], np.int64), np.array([1.0, 2.0]),
                       tags=[None, tg])  # untagged THEN tagged, one chunk
        log.flush()
        db2 = Database(ShardSet(2), clock=lambda: now["t"])
        db2.ensure_namespace(NS, NamespaceOptions())
        bs.BootstrapProcess(
            chain=("commitlog", "uninitialized_topology"),
            ctx=bs.BootstrapContext(
                commitlog_dir=os.path.join(root, "cl"),
                shard_lookup=db2.shard_set.lookup)).run(db2, now_ns=now["t"])
        got = db2.query_ids(NS, TermQuery(b"__name__", b"mix"))
        assert sorted(got) == [b"mix;host=a"]

    def test_async_insert_queue_never_loses_to_snapshot_position(
            self, tmp_path, rng):
        """write_new_series_async: an acked write can sit in the insert
        queue with its WAL append already durable. A snapshot cut at
        that moment records a position COVERING the entry's chunk — the
        snapshot must therefore contain the entry (queues drain between
        position and buffer read), else position-filtered replay drops
        it on restart: silent acked-data loss."""
        root = str(tmp_path)
        now = {"t": T0 + xtime.MINUTE}
        log = cl.CommitLog(os.path.join(root, "cl"))
        db = Database(ShardSet(2), commitlog=log, clock=lambda: now["t"])
        db.create_namespace(NS, NamespaceOptions(
            index_enabled=False, write_new_series_async=True))
        pm = PersistManager(os.path.join(root, "data"))
        db.write_batch(NS, [b"async-1", b"async-2"],
                       np.full(2, T0, np.int64), np.array([7.0, 8.0]))
        # The writes are acked (WAL durable via the snapshot's flush)
        # but still queued: no tick, no drain yet.
        assert any(sh.insert_queue.pending()
                   for sh in db.namespace(NS).shards.values())
        Mediator(db, pm).snapshot(now["t"])
        db2 = Database(ShardSet(2), clock=lambda: now["t"])
        db2.create_namespace(NS, NamespaceOptions(index_enabled=False))
        proc = bs.BootstrapProcess(
            chain=("commitlog",),
            ctx=bs.BootstrapContext(
                persist=pm, commitlog_dir=os.path.join(root, "cl"),
                shard_lookup=db2.shard_set.lookup))
        proc.run(db2, now_ns=now["t"])
        for sid, want in ((b"async-1", 7.0), (b"async-2", 8.0)):
            t, v = db2.read(NS, sid, 0, now["t"] + 1)
            assert v.tolist() == [want], f"acked async write lost: {sid!r}"

    def test_skipped_replay_is_surfaced(self, tmp_path, rng):
        """Satellite: no shard_lookup + a partial shard set must COUNT
        the skip, and surface it on the BootstrapResult notes."""
        root = str(tmp_path)
        now = {"t": T0 + xtime.MINUTE}
        log = cl.CommitLog(os.path.join(root, "cl"))
        db = Database(ShardSet(4), commitlog=log, clock=lambda: now["t"])
        db.create_namespace(NS, NamespaceOptions(index_enabled=False))
        db.write_batch(NS, [b"skip-1"], np.array([T0], np.int64),
                       np.array([1.0]))
        log.close()
        # A node owning a PARTIAL shard set: murmur%N would misroute.
        db2 = Database(ShardSet(4), clock=lambda: now["t"])
        db2.create_namespace(NS, NamespaceOptions(index_enabled=False))
        ns2 = db2.namespace(NS)
        for sid in (1, 3):
            ns2.shards.pop(sid)
        before = ROOT.sub_scope("bootstrap.commitlog") \
                     .counter("replay_skipped").value()
        proc = bs.BootstrapProcess(
            chain=("commitlog",),
            ctx=bs.BootstrapContext(commitlog_dir=os.path.join(root, "cl")))
        results = proc.run(db2, now_ns=now["t"])
        after = ROOT.sub_scope("bootstrap.commitlog") \
                    .counter("replay_skipped").value()
        assert after == before + 1
        assert any("SKIPPED" in n for n in results[NS].notes)
        # With a proper lookup the same shape replays fine: no note.
        db3 = Database(ShardSet(4), clock=lambda: now["t"])
        db3.create_namespace(NS, NamespaceOptions(index_enabled=False))
        proc3 = bs.BootstrapProcess(
            chain=("commitlog",),
            ctx=bs.BootstrapContext(commitlog_dir=os.path.join(root, "cl"),
                                    shard_lookup=db3.shard_set.lookup))
        results3 = proc3.run(db3, now_ns=now["t"])
        assert results3[NS].notes == []
        t, v = db3.read(NS, b"skip-1", 0, now["t"] + 1)
        assert v.tolist() == [1.0]


# ---------------------------------------------------------------------------
# the kill -9 disaster drill
# ---------------------------------------------------------------------------


def _drill(opts):
    sc = KillRestartScenario(opts)
    try:
        return sc.verify(sc.run())
    finally:
        sc.close()


class TestKillRestartDrill:
    @pytest.mark.parametrize("seed", [7, 19])
    def test_base_drill_zero_acked_loss(self, seed):
        res = _drill(KillRestartOptions(seed=seed))
        assert res.verified_points == res.acked_points > 0
        assert res.torn_tail_bytes > 0  # torn tail was present AND dropped

    def test_namespace_migration_variant(self):
        res = _drill(KillRestartOptions(seed=11, variant="migration"))
        assert res.verified_points == res.acked_points > 0

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [3, 23])
    def test_more_seeds(self, seed):
        res = _drill(KillRestartOptions(seed=seed))
        assert res.verified_points == res.acked_points > 0

    @pytest.mark.slow
    def test_backfill_variant_rides_same_start_merge(self):
        res = _drill(KillRestartOptions(seed=5, variant="backfill"))
        assert res.backfill_points > 0
        assert res.verified_points == res.acked_points > 0
        # three generations: initial + restart + post-backfill restart
        assert len(res.restart_walls_s) == 3
