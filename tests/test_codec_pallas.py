"""Pallas codec kernels (ops/pallas_codec.py): interpret-mode parity
against the XLA paths and the scalar reference codec.

This file is the `_PALLAS_ORACLE` the m3lint unguarded-pallas-dispatch
rule points at: every kernel (pack / decode / hash) is asserted
BIT-identical to its XLA twin and to ops/ref_codec.py over a property
corpus covering the codec's hostile regions — NaN holes, rewrite-window
churn past REWRITE_THRESHOLD, int/float mode mixes, wild f64 bit
patterns, and npoints 0/1 edges. On CPU the kernels run in interpret
mode (the CPU-fallback protocol DIVERGENCES.md documents); on a real
TPU the same tests exercise compiled Mosaic kernels unchanged."""

import os

import numpy as np
import pytest

from m3_tpu.ops import pallas_codec, ref_codec, tsz
from m3_tpu.parallel import telemetry
from m3_tpu.utils import hashing


def _corpus(seed, n, w):
    """Production mix + hostile kinds (fuzz_codec's adversarial menu,
    bounded so interpret mode stays inside the test budget)."""
    rng = np.random.default_rng(seed)
    base = np.int64(rng.choice([1_700_000_000, 2**40, 7]))
    step = int(rng.choice([1, 10, 1 << 20]))
    ts = base + np.arange(w, dtype=np.int64)[None, :] * step \
        + rng.integers(0, 2, (n, w))
    ts = np.sort(ts, axis=1)
    vals = np.empty((n, w), np.float64)
    for i in range(n):
        k = i % 7
        if k == 0:  # counter (int mode)
            vals[i] = np.cumsum(rng.poisson(5.0, w)).astype(np.float64)
        elif k == 1:  # gauge 2dp (scaled-int mode)
            vals[i] = np.round(rng.normal(100, 5, w), 2)
        elif k == 2:  # raw float noise: rewrite-window churn, every
            # XOR exceeds REWRITE_THRESHOLD reuse early on
            vals[i] = rng.normal(0, 1, w)
        elif k == 3:  # sparse NaN holes
            vals[i] = np.where(rng.random(w) < 0.1, np.nan,
                               np.round(rng.normal(10, 1, w), 3))
        elif k == 4:  # constant (zero XORs)
            vals[i] = float(rng.integers(0, 100))
        elif k == 5:  # signed zeros + denormals
            picks = rng.integers(0, 4, w)
            vals[i] = np.choose(picks, [0.0, -0.0, 5e-324, -5e-324])
        else:  # wild raw f64 bit patterns (infs, NaN payloads)
            vals[i] = rng.integers(0, 2**64, w, dtype=np.uint64).view(
                np.float64)
    npoints = rng.integers(1, w + 1, n).astype(np.int32)
    npoints[0] = 0
    npoints[1] = 1
    npoints[2] = w
    return ts, vals, npoints


def _encode_args(ts, vals, npoints):
    inp = tsz.prepare_encode_inputs(ts, vals, npoints)
    return dict(dt=inp["dt"], t0=inp["t0"], vhi=inp["vhi"],
                vlo=inp["vlo"], int_mode=inp["int_mode"], k=inp["k"],
                npoints=inp["npoints"], ts_regular=inp["ts_regular"],
                delta0=inp["delta0"])


def _assert_ref_parity(words, npoints, ts_plane, vs_plane, unit_nanos):
    words = np.asarray(words)
    for r in range(words.shape[0]):
        n = int(npoints[r])
        if n == 0:
            continue
        t_ref, v_ref = ref_codec.decode(ref_codec.EncodedBlock(
            words=words[r], nbits=0, npoints=n))
        np.testing.assert_array_equal(t_ref * unit_nanos,
                                      np.asarray(ts_plane[r, :n]))
        np.testing.assert_array_equal(
            np.asarray(v_ref).view(np.uint64),
            np.asarray(vs_plane[r, :n]).view(np.uint64))


SHAPES = [(16, 16), (24, 64)]


class TestPackParity:
    @pytest.mark.parametrize("n,w", SHAPES)
    def test_pallas_pack_bit_identical_to_both_xla_packers(self, n, w):
        ts, vals, npoints = _corpus(97 + w, n, w)
        kw = _encode_args(ts, vals, npoints)
        mw = tsz.max_words_for(w)
        outs = {p: tsz.encode_batch(**kw, max_words=mw, pack=p)
                for p in ("pallas", "scatter", "tree")}
        for p in ("scatter", "tree"):
            np.testing.assert_array_equal(
                np.asarray(outs["pallas"][0]), np.asarray(outs[p][0]),
                err_msg=f"pallas vs {p}: words")
            np.testing.assert_array_equal(
                np.asarray(outs["pallas"][1]), np.asarray(outs[p][1]),
                err_msg=f"pallas vs {p}: nbits")

    def test_pallas_pack_drop_semantics_match_scatter(self):
        # an undersized max_words drops the SAME bits on both packers
        ts, vals, npoints = _corpus(3, 16, 64)
        kw = _encode_args(ts, vals, npoints)
        mw = tsz.max_words_for(64) // 2
        wp, _ = tsz.encode_batch(**kw, max_words=mw, pack="pallas")
        ws, _ = tsz.encode_batch(**kw, max_words=mw, pack="scatter")
        np.testing.assert_array_equal(np.asarray(wp), np.asarray(ws))


class TestDecodeParity:
    @pytest.mark.parametrize("n,w", SHAPES)
    def test_decode_core_matches_xla_every_key(self, n, w):
        ts, vals, npoints = _corpus(11 + w, n, w)
        words, _ = tsz.encode(ts, vals, max_words=tsz.max_words_for(w))
        words = np.asarray(words)
        pc = pallas_codec.decode_core(words, npoints, window=w)
        xc = tsz._decode_core(words, npoints, window=w)
        assert set(pc) == set(xc)
        for key in xc:
            np.testing.assert_array_equal(
                np.asarray(pc[key]), np.asarray(xc[key]),
                err_msg=f"decode_core key {key!r}")

    def test_fused_decode_plane_vs_ref_codec(self, monkeypatch):
        monkeypatch.setenv("M3_TPU_PALLAS", "1")
        ts, vals, npoints = _corpus(5, 24, 64)
        words, _ = tsz.encode(ts, vals, max_words=tsz.max_words_for(64))
        tsp, vsp = tsz.decode_plane(np.asarray(words), npoints,
                                    window=64, unit_nanos=10**9)
        _assert_ref_parity(words, npoints, tsp, vsp, 10**9)

    def test_pallas_roundtrip_vs_ref_codec(self, monkeypatch):
        # pallas pack -> pallas decode, judged against the scalar oracle
        monkeypatch.setenv("M3_TPU_PALLAS", "1")
        ts, vals, npoints = _corpus(7, 16, 16)
        kw = _encode_args(ts, vals, npoints)
        words, _ = tsz.encode_batch(**kw, max_words=tsz.max_words_for(16),
                                    pack="pallas")
        tsp, vsp = tsz.decode_plane(np.asarray(words), npoints,
                                    window=16, unit_nanos=1)
        _assert_ref_parity(words, npoints, tsp, vsp, 1)


class TestHashParity:
    def test_hash_words_matches_scalar_murmur3(self, monkeypatch):
        monkeypatch.setenv("M3_TPU_PALLAS", "1")
        rng = np.random.default_rng(13)
        ids = [bytes(rng.integers(0, 256, ln, dtype=np.uint8))
               for ln in list(rng.integers(1, 40, 200)) + [1, 2, 3, 4, 5]]
        got = hashing.hash_batch(ids)
        ref = np.array([hashing.murmur3_32(i) for i in ids], np.uint32)
        np.testing.assert_array_equal(got, ref)

    def test_hash_batch_empty_and_oversize_fall_back(self, monkeypatch):
        monkeypatch.setenv("M3_TPU_PALLAS", "1")
        assert hashing.hash_batch([]).shape == (0,)
        big = [b"x" * (4 * pallas_codec.HASH_MAX_COLS + 8)]
        assert int(hashing.hash_batch(big)[0]) == hashing.murmur3_32(big[0])


class TestDispatchGate:
    def test_env_semantics(self, monkeypatch):
        monkeypatch.setenv("M3_TPU_PALLAS", "1")
        assert pallas_codec.enabled() is True
        monkeypatch.setenv("M3_TPU_PALLAS", "0")
        assert pallas_codec.enabled() is False
        monkeypatch.delenv("M3_TPU_PALLAS")
        import jax
        assert pallas_codec.enabled() is (jax.default_backend() == "tpu")

    def test_route_counters_prove_dispatch(self, monkeypatch):
        monkeypatch.setenv("M3_TPU_PALLAS", "1")
        before = telemetry.snapshot().get(
            "telemetry.codec.pallas_decode", 0)
        ts, vals, npoints = _corpus(17, 16, 16)
        words, _ = tsz.encode(ts, vals, max_words=tsz.max_words_for(16))
        tsz.decode_plane(np.asarray(words), npoints, window=16,
                         unit_nanos=1)
        after = telemetry.snapshot().get(
            "telemetry.codec.pallas_decode", 0)
        assert after == before + 1

    def test_kill_switch_routes_to_xla(self, monkeypatch):
        monkeypatch.setenv("M3_TPU_PALLAS", "0")
        before = telemetry.snapshot().get("telemetry.codec.xla_decode", 0)
        ts, vals, npoints = _corpus(19, 16, 16)
        words, _ = tsz.encode(ts, vals, max_words=tsz.max_words_for(16))
        tsz.decode_plane(np.asarray(words), npoints, window=16,
                         unit_nanos=1)
        after = telemetry.snapshot().get("telemetry.codec.xla_decode", 0)
        assert after == before + 1


class TestCursorOverflow:
    def test_encode_block_raises_on_undersized_bound(self):
        from m3_tpu.storage import block as blk
        ts, vals, npoints = _corpus(23, 16, 64)
        npoints = np.maximum(npoints, 1)
        with pytest.raises(tsz.CursorOverflowError):
            blk.encode_block(0, np.arange(16), ts * 10**9, vals, npoints,
                             max_words=2)

    def test_encode_raises_on_undersized_bound(self):
        ts, vals, npoints = _corpus(31, 16, 64)
        with pytest.raises(tsz.CursorOverflowError):
            tsz.encode(ts, vals, max_words=2)

    def test_max_words_for_is_sufficient(self):
        # the derived bound never trips the overflow check
        ts, vals, npoints = _corpus(29, 16, 16)
        words, nbits = tsz.encode(ts, vals,
                                  max_words=tsz.max_words_for(16))
        assert int(np.max(np.asarray(nbits))) <= 32 * tsz.max_words_for(16)


class TestGuardRouteMatrix:
    """The M3_TPU_PALLAS route matrix under the guard's per-kernel kill
    switches: guard.set_disabled("codec.<kernel>") flips each codec
    kernel's route independently, MID-PROCESS (no env churn, no cache
    surgery — the route pickers resolve outside jit per call), with the
    route counters proving the dispatch actually moved and bit-identity
    holding on both sides of every flip."""

    KERNELS = ("encode", "decode", "hash")

    @pytest.fixture(autouse=True)
    def _clean_guard(self):
        from m3_tpu.parallel import guard
        guard.reset()
        yield
        guard.reset()

    def _counts(self):
        snap = telemetry.snapshot()
        return {k: snap.get(f"telemetry.codec.{k}", 0)
                for k in ("pallas_encode", "xla_encode", "pallas_decode",
                          "xla_decode", "pallas_hash", "xla_hash")}

    @staticmethod
    def _bits(a):
        a = np.asarray(a)
        return a.view(np.uint64) if a.dtype == np.float64 else a

    def test_per_kernel_kill_switch_matrix(self, monkeypatch):
        from m3_tpu.parallel import guard
        monkeypatch.setenv("M3_TPU_PALLAS", "1")
        ts, vals, npoints = _corpus(41, 16, 16)
        kw = _encode_args(ts, vals, npoints)
        mw = tsz.max_words_for(16)
        rng = np.random.default_rng(43)
        ids = [bytes(rng.integers(0, 256, ln, dtype=np.uint8))
               for ln in rng.integers(1, 33, 64)]

        def run_all():
            words, nbits = tsz.encode_batch(**kw, max_words=mw)
            tsp, vsp = tsz.decode_plane(np.asarray(words), npoints,
                                        window=16, unit_nanos=1)
            return (np.asarray(words), np.asarray(nbits),
                    np.asarray(tsp), np.asarray(vsp),
                    hashing.hash_batch(ids))

        base = self._counts()
        ref = run_all()  # all three kernels on the pallas route
        after = self._counts()
        for kern in self.KERNELS:
            assert after[f"pallas_{kern}"] == base[f"pallas_{kern}"] + 1

        for kern in self.KERNELS:  # flip ONE switch at a time
            guard.set_disabled(f"codec.{kern}", True)
            before = self._counts()
            got = run_all()
            now = self._counts()
            # the killed kernel re-routed to its XLA/host twin...
            assert now[f"xla_{kern}"] == before[f"xla_{kern}"] + 1
            # ...the other two kept their pallas route (independence)...
            for other in self.KERNELS:
                if other != kern:
                    assert now[f"pallas_{other}"] == \
                        before[f"pallas_{other}"] + 1, (kern, other)
            # ...and every output is bit-identical across the flip.
            for a, b in zip(ref, got):
                np.testing.assert_array_equal(self._bits(a),
                                              self._bits(b), err_msg=kern)
            guard.set_disabled(f"codec.{kern}", False)

        before = self._counts()  # all switches restored: pallas again
        got = run_all()
        now = self._counts()
        for kern in self.KERNELS:
            assert now[f"pallas_{kern}"] == before[f"pallas_{kern}"] + 1
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(self._bits(a), self._bits(b))
