"""M3QL parser tests (reference: src/query/parser/m3ql/grammar_test.go —
pipelines, keyword arguments, macros, nesting, comments)."""

import pytest

from m3_tpu.query.m3ql import M3QLError, Call, Pipeline, parse


def test_simple_pipeline():
    s = parse("fetch name:cpu.util host:web* | transform perSecond")
    assert s.macros == ()
    assert [c.name for c in s.pipeline.stages] == ["fetch", "transform"]
    fetch = s.pipeline.stages[0]
    assert fetch.kwargs == (("name", "cpu.util"), ("host", "web*"))
    assert s.pipeline.stages[1].args == ("perSecond",)


def test_operator_stage_and_numbers():
    s = parse("fetch name:errors | > 0.5")
    gt = s.pipeline.stages[1]
    assert gt.name == ">" and gt.args == (0.5,)


def test_booleans_and_strings():
    s = parse('fetch name:x | summarize 1h sum alignToFrom:true '
              '| alias "cpu usage"')
    assert s.pipeline.stages[1].kwargs == (("alignToFrom", True),)
    assert s.pipeline.stages[2].args == ("cpu usage",)


def test_macro_definition_and_splice():
    s = parse("cpu = fetch name:cpu.util | transform perSecond;\n"
              "cpu | moving 5min avg")
    assert s.macros[0][0] == "cpu"
    # macro reference splices its stages into the pipeline
    assert [c.name for c in s.pipeline.stages] == [
        "fetch", "transform", "moving"]


def test_nested_pipeline_argument():
    s = parse("asPercent (fetch name:used) (fetch name:total)")
    top = s.pipeline.stages[0]
    assert top.name == "asPercent"
    assert all(isinstance(a, Pipeline) for a in top.args)
    assert top.args[0].stages[0].kwargs == (("name", "used"),)


def test_comments_and_whitespace():
    s = parse("# top-level comment\nfetch name:x  # trailing\n | head 5")
    assert [c.name for c in s.pipeline.stages] == ["fetch", "head"]
    assert s.pipeline.stages[1].args == (5.0,)


def test_float_lookalikes_stay_strings():
    """Identifier/pattern arguments that Python's float() happens to accept
    must NOT parse as numbers (the reference PEG's Number rule is
    digit-based)."""
    s = parse("fetch name:inf | filter host:1_000 | keep nan")
    assert s.pipeline.stages[0].kwargs == (("name", "inf"),)
    assert s.pipeline.stages[1].kwargs == (("host", "1_000"),)
    assert s.pipeline.stages[2].args == ("nan",)
    s2 = parse("head 5 | scale -0.5 | shift 1e3")
    assert s2.pipeline.stages[0].args == (5.0,)
    assert s2.pipeline.stages[1].args == (-0.5,)
    assert s2.pipeline.stages[2].args == (1000.0,)


def test_parse_errors():
    with pytest.raises(M3QLError):
        parse("fetch name:x |")
    with pytest.raises(M3QLError):
        parse("(fetch name:x")
    with pytest.raises(M3QLError):
        parse("m = fetch name:x")  # macro def missing ';' + pipeline
