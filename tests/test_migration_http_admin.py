"""Dual-format ingestion migration + aggregator HTTP admin server
(reference test model: src/metrics/encoding/migration/
unaggregated_iterator_test.go mixed msgpack/protobuf streams, and
src/aggregator/server/http/handlers.go health/status/resign)."""

import json
import socket
import time
import urllib.error
import urllib.request

from m3_tpu.aggregator import Aggregator, CaptureHandler
from m3_tpu.aggregator.migration import (MIGRATION_MAX_FRAME,
                                         MigrationReader,
                                         RecoverableRecordError,
                                         legacy_to_entry, write_legacy)
from m3_tpu.aggregator.server import (HTTPAdminServer, RawTCPServer,
                                      TCPTransport, union_to_wire)
from m3_tpu.metrics.metadata import Metadata, PipelineMetadata, StagedMetadata
from m3_tpu.metrics.metric import MetricType, MetricUnion
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.rpc import wire
from m3_tpu.testing.cluster import SettableClock

S = 1_000_000_000
TEN_S = StoragePolicy.of("10s", "2d")


def _await(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_legacy_to_entry_conversion():
    entry = legacy_to_entry({"type": "counter", "id": "req.count",
                             "value": 7, "policies": ["10s:2d", "1m:40d"]})
    assert entry["t"] == "untimed"
    assert entry["mtype"] == int(MetricType.COUNTER)
    assert entry["id"] == b"req.count"
    assert entry["value"] == 7
    pipelines = entry["metadatas"][0]["pipelines"]
    assert pipelines[0]["policies"] == ["10s:2d", "1m:40d"]
    assert pipelines[0]["agg_id"] == 0 and pipelines[0]["pipeline"] == []

    timer = legacy_to_entry({"type": "timer", "id": "lat",
                             "value": [1, 2.5], "policies": ["10s:2d"]})
    assert timer["value"] == [1.0, 2.5]

    try:
        legacy_to_entry({"type": "histogram", "id": "x", "value": 1})
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_mixed_format_stream_one_connection():
    """Current binary frames and legacy JSON lines interleaved on ONE
    connection all land in the same aggregation (the migration scenario:
    a proxy multiplexing migrated and unmigrated clients)."""
    clock = SettableClock(100 * S)
    cap = CaptureHandler()
    agg = Aggregator(num_shards=8, clock=clock, flush_handler=cap)
    srv = RawTCPServer(agg).start()
    try:
        host, _, port = srv.endpoint.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=5)
        md = (StagedMetadata(0, False, Metadata(
            (PipelineMetadata(0, (TEN_S,)),))),)
        # binary frame (current generation)
        wire.write_frame(sock, union_to_wire(
            MetricUnion.counter(b"mixed.count", 3), md))
        # legacy line (old generation), same metric id -> same entry
        write_legacy(sock, "counter", "mixed.count", 4, ["10s:2d"])
        # binary again: the reader switches per message, not per connection
        wire.write_frame(sock, union_to_wire(
            MetricUnion.counter(b"mixed.count", 5), md))
        assert _await(lambda: srv.frames >= 3)
        assert agg.num_entries() == 1
        clock.advance(10 * S)
        agg.flush()
        out = cap.by_id(b"mixed.count")
        assert len(out) == 1 and out[0].value == 12.0
        assert srv.errors == 0
        sock.close()
    finally:
        srv.close()


def test_legacy_only_client_gauge_and_timer():
    clock = SettableClock(100 * S)
    cap = CaptureHandler()
    agg = Aggregator(num_shards=8, clock=clock, flush_handler=cap)
    srv = RawTCPServer(agg).start()
    try:
        host, _, port = srv.endpoint.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=5)
        write_legacy(sock, "gauge", "legacy.gauge", 42.5, ["10s:2d"])
        write_legacy(sock, "timer", "legacy.timer", [1.0, 3.0, 2.0],
                     ["10s:2d"])
        assert _await(lambda: srv.frames >= 2)
        clock.advance(10 * S)
        agg.flush()
        gauges = cap.by_id(b"legacy.gauge")
        assert len(gauges) == 1 and gauges[0].value == 42.5
        # Timer default aggregations emit suffixed ids; just check presence.
        assert any(m.id.startswith(b"legacy.timer") for m in cap.metrics)
    finally:
        srv.close()


def test_bad_legacy_record_does_not_kill_connection():
    """A malformed legacy record is consumed and counted; later messages on
    the same connection still ingest (the binary-framing error path, by
    contrast, closes the stream)."""
    clock = SettableClock(100 * S)
    cap = CaptureHandler()
    agg = Aggregator(num_shards=8, clock=clock, flush_handler=cap)
    srv = RawTCPServer(agg).start()
    try:
        host, _, port = srv.endpoint.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=5)
        write_legacy(sock, "histogram", "bad.type", 1, ["10s:2d"])  # unknown
        write_legacy(sock, "counter", "good.count", 2, ["10s:2d"])
        assert _await(lambda: srv.frames >= 1)
        assert srv.errors == 1
        clock.advance(10 * S)
        agg.flush()
        out = cap.by_id(b"good.count")
        assert len(out) == 1 and out[0].value == 2.0
    finally:
        srv.close()


def test_migration_reader_oversize_frame_rejected():
    a, b = socket.socketpair()
    try:
        a.sendall((MIGRATION_MAX_FRAME + 1).to_bytes(4, "little") + b"x")
        reader = MigrationReader(b)
        try:
            reader.read_entries()
            raise AssertionError("expected ValueError")
        except ValueError:
            pass
    finally:
        a.close()
        b.close()


def test_migration_reader_desync_line_is_unrecoverable():
    """Bytes that sniff as a legacy line (byte0=='{', byte3!=0) but are not
    JSON mean the sniff mis-fired on binary data — the consumed-to-newline
    bytes desynchronized the stream, so the reader must raise a plain
    (connection-tearing) error, NOT RecoverableRecordError."""
    a, b = socket.socketpair()
    try:
        a.sendall(b"\x7b\xff\xfe\xfd\x00\x01binary\n")
        reader = MigrationReader(b)
        try:
            reader.read_entries()
            raise AssertionError("expected ValueError")
        except RecoverableRecordError:
            raise AssertionError("desync must not be recoverable")
        except ValueError as e:
            assert "desync" in str(e)
    finally:
        a.close()
        b.close()


def test_http_ingest_variant():
    """HTTP ingest (src/aggregator/server/http analog + task: collectors
    behind HTTP-only paths): legacy-schema NDJSON POSTed to /ingest lands
    in the aggregator via the same dispatch as rawtcp, and the
    HTTPTransport client wraps a MetricUnion write end-to-end."""
    import json as _json
    import urllib.request

    from m3_tpu.aggregator.server import HTTPAdminServer, HTTPTransport
    from m3_tpu.metrics.metadata import (Metadata, PipelineMetadata,
                                         StagedMetadata)
    from m3_tpu.metrics.metric import MetricUnion
    from m3_tpu.metrics.policy import StoragePolicy

    clock = SettableClock(100 * S)
    cap = CaptureHandler()
    agg = Aggregator(num_shards=8, clock=clock, flush_handler=cap)
    srv = HTTPAdminServer(agg).start()
    try:
        # raw NDJSON ingest, including one bad record -> 400 + partial accept
        body = (b'{"type": "counter", "id": "http.count", "value": 7, '
                b'"policies": ["10s:2d"]}\n'
                b'{"type": "bogus", "id": "x", "value": 1}\n')
        req = urllib.request.Request(srv.endpoint + "/ingest", data=body,
                                     method="POST")
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected HTTP 400 for the bad record")
        except urllib.error.HTTPError as e:
            out = _json.loads(e.read())
            assert e.code == 400 and out["accepted"] == 1, out
        # transport client: a collector-side write over HTTP
        tr = HTTPTransport(srv.endpoint, batch_size=1)
        md = (StagedMetadata(0, False, Metadata((PipelineMetadata(
            0, (StoragePolicy.parse("10s:2d"),)),))),)
        assert tr(MetricUnion.counter(b"http.count", 5), md)
        assert agg.num_entries() == 1
        clock.advance(10 * S)
        agg.flush()
        out = cap.by_id(b"http.count")
        assert len(out) == 1 and out[0].value == 12.0  # 7 + 5 summed
    finally:
        srv.close()


def test_http_admin_health_status_resign():
    clock = SettableClock(100 * S)
    agg = Aggregator(num_shards=4, clock=clock,
                     flush_handler=CaptureHandler())
    srv = HTTPAdminServer(agg).start()
    try:
        def get(path):
            with urllib.request.urlopen(srv.endpoint + path) as r:
                return json.loads(r.read())

        assert get("/health") == {"state": "OK"}
        st = get("/status")["status"]
        # Leaderless aggregator (embedded downsampler mode) always leads.
        assert st["flushStatus"] == {"electionState": "leader",
                                     "canLead": True}
        assert st["numEntries"] == 0
        # resign without an election manager is a client error
        req = urllib.request.Request(srv.endpoint + "/resign", data=b"",
                                     method="POST")
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        try:
            get("/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.close()


def test_admin_sidecar_via_service_assembly():
    """run_aggregator with admin_address starts the sidecar; /status
    reflects the election-managed aggregator and /resign steps down."""
    from m3_tpu.services import config as svc_config
    from m3_tpu.services import run as svc_run

    cfg = svc_config.load_dict(
        {"flush_interval": "1s", "num_shards": 4,
         "admin_address": "127.0.0.1:0"}, "aggregator")
    handle = svc_run.run_aggregator(cfg, flush_handler=CaptureHandler())
    try:
        assert handle.admin_endpoint
        with urllib.request.urlopen(handle.admin_endpoint + "/health") as r:
            assert json.loads(r.read()) == {"state": "OK"}
        with urllib.request.urlopen(handle.admin_endpoint + "/status") as r:
            st = json.loads(r.read())["status"]
        assert st["flushStatus"]["electionState"] in (
            "leader", "follower", "pending_follower")
        req = urllib.request.Request(handle.admin_endpoint + "/resign",
                                     data=b"", method="POST")
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read()) == {"state": "OK"}
    finally:
        handle.close()


def test_columnar_timed_batch_roundtrip():
    """A tbatch frame (columnar timed batch) lands every datapoint in the
    right windows — conservation against per-entry timed frames carrying
    the same data — and the server counts one RECORD per id."""
    import numpy as np

    clock = SettableClock(1_700_000_000 * S)
    cap = CaptureHandler()
    agg = Aggregator(num_shards=8, clock=clock, flush_handler=cap)
    srv = RawTCPServer(agg).start()
    try:
        t0 = 1_700_000_000 * S
        n = 300
        ids = [b"tb.%d" % (i % 50) for i in range(n)]
        times = np.array([t0 + (i % 3) * 10 * S for i in range(n)], np.int64)
        values = np.arange(n, dtype=np.float64)

        host, _, port = srv.endpoint.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=5)
        wire.write_frame(sock, {
            "t": "tbatch", "mtype": int(MetricType.COUNTER),
            "policy": "10s:2d", "agg_id": 0,
            "ids": ids, "times": times, "values": values})
        assert _await(lambda: srv.frames >= n)  # records, not frames
        assert srv.errors == 0
        clock.advance(40 * S)
        agg.flush()
        # Conservation: per-(id, window) sums match a host reference.
        want = {}
        for mid, t, v in zip(ids, times.tolist(), values.tolist()):
            want[(mid, t // (10 * S))] = want.get((mid, t // (10 * S)), 0.0) + v
        got = {}
        for m in cap.metrics:
            key = (m.id, m.time_nanos // (10 * S) - 1)  # window end stamp
            got[key] = got.get(key, 0.0) + m.value
        assert sum(got.values()) == sum(want.values()) == values.sum()
        assert len(got) == len(want)
        sock.close()
    finally:
        srv.close()


def test_columnar_timed_batch_via_transport():
    """TCPTransport.send_timed_batch ships the frame the server accepts."""
    import numpy as np

    clock = SettableClock(1_700_000_000 * S)
    cap = CaptureHandler()
    agg = Aggregator(num_shards=8, clock=clock, flush_handler=cap)
    srv = RawTCPServer(agg).start()
    tr = TCPTransport(srv.endpoint)
    try:
        t0 = 1_700_000_000 * S
        assert tr.send_timed_batch(
            MetricType.GAUGE, TEN_S, [b"tg.1", b"tg.2"],
            [t0, t0], [4.5, 6.5])
        assert _await(lambda: srv.frames >= 2)
        clock.advance(10 * S)
        agg.flush()
        assert cap.by_id(b"tg.1")[0].value == 4.5
        assert cap.by_id(b"tg.2")[0].value == 6.5
    finally:
        tr.close()
        srv.close()


def test_columnar_timed_batch_length_mismatch_counts_error():
    """Malformed tbatch (ragged columns) is an application error: counted,
    connection stays up, later frames still ingest."""
    import numpy as np

    clock = SettableClock(1_700_000_000 * S)
    cap = CaptureHandler()
    agg = Aggregator(num_shards=8, clock=clock, flush_handler=cap)
    srv = RawTCPServer(agg).start()
    try:
        t0 = 1_700_000_000 * S
        host, _, port = srv.endpoint.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=5)
        wire.write_frame(sock, {
            "t": "tbatch", "mtype": int(MetricType.COUNTER),
            "policy": "10s:2d", "agg_id": 0,
            "ids": [b"ragged.1", b"ragged.2"],
            "times": np.array([t0], np.int64),          # ragged!
            "values": np.array([1.0, 2.0], np.float64)})
        # non-bytes ids must reject the WHOLE frame before any add
        # (all-or-nothing: no partial prefix may aggregate)
        wire.write_frame(sock, {
            "t": "tbatch", "mtype": int(MetricType.COUNTER),
            "policy": "10s:2d", "agg_id": 0,
            "ids": [b"typed.ok", "typed.bad-str"],
            "times": np.array([t0, t0], np.int64),
            "values": np.array([1.0, 2.0], np.float64)})
        wire.write_frame(sock, {
            "t": "timed", "mtype": int(MetricType.COUNTER),
            "id": b"after.ragged", "time": t0, "value": 7.0,
            "policy": "10s:2d"})
        # errors count RECORDS, same unit as frames: 2 per failed tbatch
        assert _await(lambda: srv.errors >= 4)
        assert _await(lambda: srv.frames >= 1)
        clock.advance(10 * S)
        agg.flush()
        assert cap.by_id(b"after.ragged")[0].value == 7.0
        # nothing from either rejected tbatch aggregated — incl. the
        # well-typed first row of the mixed-type frame
        assert not cap.by_id(b"typed.ok")
        assert not cap.by_id(b"ragged.1")
        sock.close()
    finally:
        srv.close()
