"""Query EXPLAIN/ANALYZE observatory tests: the typed fallback taxonomy
(every `NotCompilable` raise site uses a catalogued `FallbackReason`;
reason-tagged `telemetry.plan_fallback` counters), the EXPLAIN plan tree
(per-node kind/sharding/route, the failing node pinned with its exact
reason), the ANALYZE instrumented execution mode (stage wall times with
zero cost when disabled), the slow-query ring's route/fallback fields,
the opt-in corpus recorder + coverage computation, and the coordinator
HTTP surfaces (/debug/explain, ?explain=true beside data)."""

import ast as pyast
import inspect
import json
import os
import urllib.request

import numpy as np
import pytest

from m3_tpu.query import Engine, promql
from m3_tpu.query import corpus as qcorpus
from m3_tpu.query import explain as qexplain
from m3_tpu.query import plan as qplan
from m3_tpu.query.executor import DEFAULT_LOOKBACK_NS, QueryParams
from m3_tpu.query.plan import FallbackReason
from m3_tpu.utils.instrument import ROOT
from m3_tpu.utils.tracing import SLOW_QUERIES

S = 1_000_000_000
T0 = 1_700_000_000 * S
RES = 10 * S
NPTS = 200
STEP = 30 * S
START, END = T0 + 40 * RES, T0 + (NPTS - 1) * RES

PARAMS = QueryParams(START, END, STEP)


class MemStorage:
    def __init__(self, n=64):
        t = T0 + np.arange(NPTS, dtype=np.int64) * RES
        self.series = {}
        for i in range(n):
            self.series[b"m%d" % i] = {
                "tags": {b"__name__": b"m", b"host": b"h%d" % (i % 4),
                         b"i": str(i).encode()},
                "t": t,
                "v": 1e9 * (1 + i % 3) + np.cumsum(
                    np.full(NPTS, 3.0)) + i}
        for i in range(n // 4):
            self.series[b"b%d" % i] = {
                "tags": {b"__name__": b"b", b"host": b"h%d" % (i % 4),
                         b"i": str(i).encode()},
                "t": t, "v": np.full(NPTS, 10.0) + i}

    def fetch_raw(self, matchers, start_ns, end_ns):
        out = {}
        for sid, rec in self.series.items():
            if all(m.matches(rec["tags"].get(m.name, b"")) for m in matchers):
                out[sid] = rec
        return out


@pytest.fixture
def no_floor(monkeypatch):
    monkeypatch.setattr(qplan, "PLAN_MIN_CELLS", 1)


def _explain(q):
    return qexplain.explain(promql.parse(q), PARAMS, DEFAULT_LOOKBACK_NS,
                            query=q)


# ------------------------------------------------------- fallback taxonomy


class TestFallbackTaxonomy:
    def test_every_raise_site_uses_catalogued_reason(self):
        """Satellite: no free-form NotCompilable strings can creep back
        in — every construction in query/plan.py passes a FallbackReason
        attribute as its first argument."""
        src = inspect.getsource(qplan)
        tree = pyast.parse(src)
        checked = 0
        for node in pyast.walk(tree):
            if not (isinstance(node, pyast.Call)
                    and isinstance(node.func, pyast.Name)
                    and node.func.id == "NotCompilable"):
                continue
            # The class definition's super().__init__ body is not a Call
            # to NotCompilable, so every match here is a raise/construct
            # site.
            assert node.args, "NotCompilable() constructed with no reason"
            first = node.args[0]
            assert isinstance(first, pyast.Attribute) and \
                isinstance(first.value, pyast.Name) and \
                first.value.id == "FallbackReason", (
                    f"line {node.lineno}: NotCompilable first arg is not "
                    "a FallbackReason attribute — free-form reason "
                    "strings are banned")
            assert first.attr in FallbackReason.__members__, (
                f"line {node.lineno}: unknown reason {first.attr}")
            checked += 1
        assert checked >= 12, f"only {checked} sites scanned"

    def test_reasons_match_expected_per_query(self):
        expected = {
            # round 16 retired topk/quantile/stddev aggs, irate/idelta/
            # timestamp/quantile_over_time, subqueries and group
            # matching from this table — they lower now; what remains:
            "sum(topk(3, m))": FallbackReason.UNSUPPORTED_AGG,
            'count_values("v", m)': FallbackReason.UNSUPPORTED_AGG,
            "absent(m)": FallbackReason.UNSUPPORTED_FUNC,
            "sort(m)": FallbackReason.UNSUPPORTED_FUNC,
            "absent_over_time(m[10m:1m])": FallbackReason.UNSUPPORTED_FUNC,
            "irate(abs(m)[10m:1m])": FallbackReason.F64_ARITH,
            "m and b": FallbackReason.SET_OP,
            "m % 7": FallbackReason.F64_ARITH,
            "m > 2e9": FallbackReason.ABS_COMPARISON,
            "timestamp(m) > 2e9": FallbackReason.ABS_COMPARISON,
            "m[5m]": FallbackReason.MATRIX_SELECTOR,
            "m @ 100": FallbackReason.AT_MODIFIER,
            "2 + 2": FallbackReason.SCALAR_ONLY,
            "clamp_min(m, scalar(b))": FallbackReason.NON_CONSTANT_PARAM,
        }
        for q, want in expected.items():
            plan, err, _ = qplan.lower_and_collect(
                promql.parse(q), PARAMS, DEFAULT_LOOKBACK_NS)
            assert plan is None, q
            assert err.reason is want, f"{q}: {err.reason} != {want}"

    def test_retired_reasons_gone(self):
        """Round 16: the lowered families' members are GONE from the
        taxonomy, not parked at zero."""
        values = {r.value for r in FallbackReason}
        assert "subquery" not in values
        assert "group-matching" not in values

    def test_telemetry_counts_reason_and_scope_tagged(self, no_floor):
        eng = Engine(MemStorage())
        before = ROOT.snapshot()
        eng.execute_range("sum(topk(3, m))", START, END, STEP)
        after = ROOT.snapshot()
        key = ("telemetry.plan_fallback.count"
               "{reason=unsupported-agg,scope=structural}")
        assert after.get(key, 0) - before.get(key, 0) == 1
        assert after.get("telemetry.plan_fallback.total", 0) \
            - before.get("telemetry.plan_fallback.total", 0) == 1

    def test_below_floor_counted(self):
        eng = Engine(MemStorage(n=2))
        before = ROOT.snapshot()
        eng.execute_range("sum(rate(m[5m]))", START, END, STEP).values
        after = ROOT.snapshot()
        # Satellite regression: a below-floor data-dependent miss tags
        # scope=runtime — it must never read as a structural lowering
        # gap (coverage_report.py's structural replay would disagree).
        key = ("telemetry.plan_fallback.count"
               "{reason=below-floor,scope=runtime}")
        assert after.get(key, 0) - before.get(key, 0) == 1
        assert eng.last_route()["fallback_reason"] == "below-floor"
        assert qplan.fallback_scope("below-floor") == "runtime"
        assert qplan.fallback_scope("unsupported-agg") == "structural"

    def test_plan_fallback_exception_carries_backend_gap(self):
        from m3_tpu.parallel.compile import PlanFallback

        e = PlanFallback("weird shape")
        assert e.reason is FallbackReason.BACKEND_GAP
        assert "backend-gap" in str(e)


# ----------------------------------------------------------------- EXPLAIN


class TestExplainTree:
    def test_compiled_tree_nodes_and_sharding(self):
        out = _explain("sum by (host) (rate(m[5m]))")
        assert out["route"] == "compiled"
        assert out["fallback_reason"] is None
        assert out["mesh_ok"] is True
        nodes = list(qexplain.walk(out["root"]))
        kinds = [n["node"] for n in nodes]
        assert kinds == ["Aggregate", "RangeFunc", "Fetch"]
        assert all(n["route"] == "compiled" for n in nodes)
        # The aggregate's output replicates; the fetch rows shard.
        assert nodes[0]["sharding"] == qplan.REPLICATED
        assert nodes[2]["sharding"] == qplan.SHARDED
        assert nodes[2]["kind"] == qplan.SERIES

    def test_vv_match_not_mesh_ok(self):
        out = _explain("m * on(host, i) b")
        assert out["route"] == "compiled"
        assert out["mesh_ok"] is False

    def test_output_stable(self):
        for q in ("sum by (host) (rate(m[5m]))", "topk(3, m)"):
            assert _explain(q) == _explain(q)

    def test_fallback_tree_pins_reason_on_raising_node(self):
        out = _explain("sum(topk(3, m))")
        assert out["route"] == "interpreter"
        assert out["fallback_reason"] == "unsupported-agg"
        nodes = list(qexplain.walk(out["root"]))
        assert all(n["route"] == "interpreter" for n in nodes)
        culprits = [n for n in nodes if "reason" in n]
        assert len(culprits) == 1
        assert culprits[0]["node"] == "Aggregation"
        assert culprits[0]["detail"] == "topk"
        assert culprits[0]["reason"] == "unsupported-agg"

    def test_fallback_reason_matches_lowering(self):
        for q in ("sum(topk(3, m))", "m and b", "m > 2e9",
                  "irate(abs(m)[10m:1m])"):
            out = _explain(q)
            _, err, _ = qplan.lower_and_collect(
                promql.parse(q), PARAMS, DEFAULT_LOOKBACK_NS)
            assert out["fallback_reason"] == err.reason.value, q


# ---------------------------------------------------------------- slow ring


class TestSlowRingRoute:
    def test_slow_interpreted_query_records_fallback_reason(
            self, monkeypatch, no_floor):
        """Satellite regression: a slow interpreted query's ring entry
        carries the plan fallback reason (pre-change only the span had
        the route tag — the ring gave the operator no WHY)."""
        monkeypatch.setattr(SLOW_QUERIES, "threshold_ns", 0)
        eng = Engine(MemStorage())
        SLOW_QUERIES.clear()
        eng.execute_range("sum(topk(3, m))", START, END, STEP)
        entries = [e for e in SLOW_QUERIES.entries()
                   if e["name"] == "sum(topk(3, m))"]
        assert entries, "slow entry missing"
        assert entries[-1]["route"] == "interpreter"
        assert entries[-1]["plan_fallback"] == "unsupported-agg"

    def test_compiled_entry_has_route_no_fallback(self, monkeypatch,
                                                  no_floor):
        monkeypatch.setattr(SLOW_QUERIES, "threshold_ns", 0)
        eng = Engine(MemStorage())
        SLOW_QUERIES.clear()
        eng.execute_range("sum by (host) (rate(m[5m]))", START, END,
                          STEP).values
        entries = [e for e in SLOW_QUERIES.entries()
                   if e["name"] == "sum by (host) (rate(m[5m]))"]
        assert entries[-1]["route"] == "compiled"
        assert "plan_fallback" not in entries[-1]


# ----------------------------------------------------------------- ANALYZE


class TestAnalyze:
    def test_plan_route_stages(self, no_floor):
        eng = Engine(MemStorage())
        with qexplain.analyzing() as actx:
            eng.execute_range("sum by (host) (rate(m[5m]))", START, END,
                              STEP).values
        d = actx.to_dict()
        assert "bind" in d["stages_ms"]
        dev = [k for k in d["stages_ms"] if k.startswith("device_program[")]
        assert dev, d["stages_ms"]
        assert "result_materialize" in d["stages_ms"]
        assert d["events"].get("d2h_bytes", 0) > 0
        assert d["events"].get("grid_cache_miss", 0) \
            + d["events"].get("grid_cache_hit", 0) >= 1

    def test_interpreter_route_stage(self, no_floor):
        eng = Engine(MemStorage())
        with qexplain.analyzing() as actx:
            eng.execute_range("sum(topk(3, m))", START, END, STEP)
        assert "interpreter_eval" in actx.to_dict()["stages_ms"]

    def test_disabled_is_inert(self, no_floor):
        assert qexplain.current() is None
        eng = Engine(MemStorage())
        eng.execute_range("sum(m)", START, END, STEP).values
        assert qexplain.current() is None

    def test_context_restores_previous(self):
        with qexplain.analyzing() as outer:
            with qexplain.analyzing() as inner:
                assert qexplain.current() is inner
            assert qexplain.current() is outer
        assert qexplain.current() is None


# ------------------------------------------------------------------ corpus


class TestCorpusNormalize:
    def test_label_values_and_literals_stripped(self):
        shape = qcorpus.normalize(
            'sum by (host) (rate(http_req{job="secret-app",'
            'inst=~"prod-.*"}[5m])) > 99.5')
        assert "secret-app" not in shape and "prod-" not in shape
        assert "99.5" not in shape
        assert "job=" in shape and "inst=~" in shape  # names survive
        assert "[300s]" in shape                      # durations survive

    def test_normalized_shape_preserves_route(self):
        queries = [
            "sum by (host) (rate(m[5m]))", "topk(3, m)",
            "max_over_time(rate(m[5m])[10m:1m])", "m > 2e9", "m and b",
            'rate(m{host="h1"}[7m])', "clamp(m, 10, 60)",
            "m * on(host, i) b", "histogram_quantile(0.9, m)",
            "sum(m offset 5m)", "quantile_over_time(0.9, m[5m])",
        ]
        for q in queries:
            shape = qcorpus.normalize(q)
            p1, e1, _ = qplan.lower_and_collect(
                promql.parse(q), PARAMS, DEFAULT_LOOKBACK_NS)
            p2, e2, _ = qplan.lower_and_collect(
                promql.parse(shape), PARAMS, DEFAULT_LOOKBACK_NS)
            assert (p1 is None) == (p2 is None), (q, shape)
            if p1 is None:
                assert e1.reason is e2.reason, (q, shape)

    def test_normalize_idempotent(self):
        for q in ("sum by (host) (rate(m[5m]))", "(m) > (1)",
                  "topk (1, m)"):
            once = qcorpus.normalize(q)
            assert qcorpus.normalize(once) == once


class TestCorpusRecorder:
    def test_bounded_and_counts(self, tmp_path):
        path = str(tmp_path / "corpus.jsonl")
        rec = qcorpus.CorpusRecorder(path, sample=1.0, max_records=3)
        for i in range(5):
            rec.record("sum(m)", route="compiled", series=i)
        assert rec.written == 3 and rec.dropped == 2
        assert len(qcorpus.read_corpus(path)) == 3
        # A restart counts the existing lines against the bound.
        rec2 = qcorpus.CorpusRecorder(path, sample=1.0, max_records=3)
        assert rec2.record("sum(m)") is False
        assert rec2.dropped == 1

    def test_sample_zero_records_nothing(self, tmp_path):
        path = str(tmp_path / "c.jsonl")
        rec = qcorpus.CorpusRecorder(path, sample=0.0)
        assert rec.record("sum(m)") is False
        assert not os.path.exists(path)

    def test_unparseable_query_counts_error_not_raise(self, tmp_path):
        rec = qcorpus.CorpusRecorder(str(tmp_path / "c.jsonl"), sample=1.0)
        assert rec.record("sum(((") is False
        assert rec.errors == 1

    def test_executor_integration_and_coverage(self, tmp_path, no_floor):
        path = str(tmp_path / "corpus.jsonl")
        qcorpus.install(qcorpus.CorpusRecorder(path, sample=1.0))
        try:
            eng = Engine(MemStorage())
            for q in ("sum by (host) (rate(m[5m]))", "sum(topk(3, m))",
                      "sum(m)", "m > 2e9", "sum by (host) (rate(m[5m]))"):
                eng.execute_range(q, START, END, STEP).values
        finally:
            qcorpus.install(None)
        records = qcorpus.read_corpus(path)
        assert len(records) == 5
        cov = qcorpus.coverage(records)
        assert cov["total"] == 5
        assert cov["compiled"] == 3
        assert cov["fallbacks"] == {"unsupported-agg": 1,
                                    "abs-comparison": 1}
        assert cov["compiled"] + sum(cov["fallbacks"].values()) == 5
        assert cov["structural_compiled"] == 3
        # Latency + series counts recorded per query.
        assert all(r["latency_ms"] >= 0 for r in records)
        assert any(r["series"] > 0 for r in records)

    def test_env_opt_in(self, tmp_path, monkeypatch, no_floor):
        path = str(tmp_path / "env.jsonl")
        monkeypatch.setenv("M3_TPU_QUERY_CORPUS", path)
        monkeypatch.setenv("M3_TPU_CORPUS_SAMPLE", "1.0")
        monkeypatch.setattr(qcorpus, "_RECORDER", None)
        monkeypatch.setattr(qcorpus, "_RESOLVED", False)
        try:
            eng = Engine(MemStorage())
            eng.execute_range("sum(m)", START, END, STEP).values
        finally:
            qcorpus.install(None)
        assert len(qcorpus.read_corpus(path)) == 1

    def test_maybe_record_materializes_lazy_result(self, tmp_path):
        """Review regression: a sampled compiled query's lazy result
        materializes INSIDE the hook, so recorded latency includes the
        d2h transfer — symmetric with the eager interpreter route."""
        import time as _time

        path = str(tmp_path / "lazy.jsonl")
        qcorpus.install(qcorpus.CorpusRecorder(path, sample=1.0))
        touched = {}

        class FakeLazy:
            series_tags = [object(), object()]

            @property
            def values(self):
                touched["materialized"] = True
                return np.zeros((2, 1))

        try:
            qcorpus.maybe_record("sum(m)", {"route": "compiled"},
                                 FakeLazy(), _time.perf_counter_ns(),
                                 30 * S)
        finally:
            qcorpus.install(None)
        assert touched.get("materialized")
        recs = qcorpus.read_corpus(path)
        assert len(recs) == 1 and recs[0]["series"] == 2

    def test_corrupt_lines_skipped(self, tmp_path):
        path = str(tmp_path / "c.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps({"shape": "sum(m)", "route": "compiled"})
                    + "\n")
            f.write("{torn line\n")
        assert len(qcorpus.read_corpus(path)) == 1


# ------------------------------------------------------------ HTTP surface


def _get(url):
    with urllib.request.urlopen(url) as resp:
        return json.loads(resp.read())


@pytest.fixture
def api(no_floor):
    from m3_tpu.coordinator.http_api import HTTPApi

    api = HTTPApi(Engine(MemStorage())).serve()
    yield api
    api.close()


class TestExplainHTTP:
    def _url(self, api, q, **extra):
        from urllib.parse import urlencode

        params = {"query": q, "start": START / S, "end": END / S,
                  "step": "30", **extra}
        return f"{api.endpoint}/debug/explain?{urlencode(params)}"

    def test_debug_explain_compiled(self, api):
        out = _get(self._url(api, "sum by (host) (rate(m[5m]))"))
        assert out["route"] == "compiled"
        assert out["root"]["node"] == "Aggregate"
        assert all(n["route"] == "compiled"
                   for n in qexplain.walk(out["root"]))

    def test_debug_explain_fallback_reason(self, api):
        out = _get(self._url(api, "m and b"))
        assert out["route"] == "interpreter"
        assert out["fallback_reason"] == "set-op"
        culprits = [n for n in qexplain.walk(out["root"]) if "reason" in n]
        assert culprits and culprits[0]["reason"] == "set-op"

    def test_debug_explain_new_node_kinds(self, api):
        """Satellite: EXPLAIN shows the round-16 node kinds with their
        mesh sharding annotations."""
        out = _get(self._url(api, "max_over_time(rate(m[5m])[10m:1m])"))
        assert out["route"] == "compiled"
        nodes = {n["node"]: n for n in qexplain.walk(out["root"])}
        assert "SubqueryFunc" in nodes
        assert nodes["SubqueryFunc"]["sharding"] == qplan.SHARDED
        assert "subquery" in nodes["SubqueryFunc"]["detail"]
        assert out["mesh_ok"] is True

        out = _get(self._url(api, "topk(3, m)"))
        assert out["route"] == "compiled"
        nodes = {n["node"]: n for n in qexplain.walk(out["root"])}
        assert "RankAgg" in nodes
        assert nodes["RankAgg"]["sharding"] == qplan.REPLICATED
        assert out["mesh_ok"] is False  # cross-row sort: single-device

        out = _get(self._url(api, "m * on(host) group_left c"))
        assert out["route"] == "compiled"
        assert out["mesh_ok"] is False  # vv gather: single-device

    def test_debug_explain_analyze_executes(self, api):
        out = _get(self._url(api, "sum by (host) (rate(m[5m]))",
                             analyze="true"))
        assert out["executed"]["route"] == "compiled"
        assert "bind" in out["analyze"]["stages_ms"]
        assert any(k.startswith("device_program[")
                   for k in out["analyze"]["stages_ms"])

    def test_debug_explain_bad_query_400(self, api):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(self._url(api, "sum((("))
        assert exc.value.code == 400

    def test_query_range_explain_beside_data(self, api):
        from urllib.parse import urlencode

        params = {"query": "sum by (host) (rate(m[5m]))",
                  "start": START / S, "end": END / S, "step": "30",
                  "explain": "true"}
        out = _get(f"{api.endpoint}/api/v1/query_range?{urlencode(params)}")
        assert out["status"] == "success"
        assert out["data"]["result"], "data must still ride the response"
        exp = out["data"]["explain"]
        assert exp["route"] == "compiled"
        assert exp["executed"]["route"] == "compiled"

    def test_query_instant_explain_analyze(self, api):
        from urllib.parse import urlencode

        params = {"query": "sum by (host) (rate(m[5m]))",
                  "time": END / S, "explain": "true", "analyze": "true"}
        out = _get(f"{api.endpoint}/api/v1/query?{urlencode(params)}")
        exp = out["data"]["explain"]
        assert exp["route"] == "compiled"
        assert "stages_ms" in exp["analyze"]

    def test_query_range_without_flag_unchanged(self, api):
        from urllib.parse import urlencode

        params = {"query": "sum(m)", "start": START / S, "end": END / S,
                  "step": "30"}
        out = _get(f"{api.endpoint}/api/v1/query_range?{urlencode(params)}")
        assert "explain" not in out["data"]


class TestCoverageReportScopeSplit:
    """scripts/coverage_report.py's scope-split invariant: the
    structural|runtime fallback split must PARTITION the recorded
    fallbacks per reason — a taxonomy edit that double-counts (or
    half-counts) a reason fails the report, not just skews it."""

    def _write_corpus(self, tmp_path, records):
        path = tmp_path / "corpus.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
        return path

    def _run_report(self, path):
        import pathlib
        import subprocess
        import sys

        repo = pathlib.Path(__file__).resolve().parent.parent
        return subprocess.run(
            [sys.executable, str(repo / "scripts" / "coverage_report.py"),
             str(path)],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def test_split_partitions_and_sums(self, tmp_path):
        path = self._write_corpus(tmp_path, [
            {"shape": "sum(m)", "route": "compiled", "step_ns": 30 * S},
            {"shape": "m and b", "route": "interpreter",
             "reason": "set-op", "step_ns": 30 * S},
            # runtime-scope miss: structurally compilable, data too small
            {"shape": "sum(m)", "route": "interpreter",
             "reason": "below-floor", "step_ns": 30 * S},
        ])
        proc = self._run_report(path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "1 runtime-scope + 1 structural-scope" in proc.stdout
        assert "below-floor" in proc.stdout and "[runtime]" in proc.stdout
        assert "set-op" in proc.stdout and "[structural]" in proc.stdout

    def test_coverage_scope_split_partitions_per_reason(self):
        # The invariant the report asserts, at the library level: every
        # runtime-scope reason carries its FULL per-reason count (no
        # partial/dual classification), and scopes sum to the fallback
        # total.
        records = [
            {"shape": "sum(m)", "route": "compiled", "step_ns": 30 * S},
            {"shape": "sum(m)", "route": "interpreter",
             "reason": "below-floor", "step_ns": 30 * S},
            {"shape": "sum(m)", "route": "interpreter",
             "reason": "below-floor", "step_ns": 30 * S},
            {"shape": "m and b", "route": "interpreter",
             "reason": "set-op", "step_ns": 30 * S},
        ]
        cov = qcorpus.coverage(records)
        runtime = cov["runtime_fallbacks"]
        fb = cov["fallbacks"]
        assert set(runtime) <= set(fb)
        for reason, n in runtime.items():
            assert n == fb[reason]
        structural_scope = sum(n for r, n in fb.items() if r not in runtime)
        assert sum(runtime.values()) + structural_scope == sum(fb.values())
        assert runtime == {"below-floor": 2}
